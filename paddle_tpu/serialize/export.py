"""Shared ``jax.export`` helpers — the one program wire format.

``jit.save`` writes a serialized exported module (``.pdmodel``), the
serving engine publishes per-bucket exported programs into the
artifact store, and both read them back through here. Centralizing the
calls keeps the format decisions (and their failure modes) in one
place:

- ``serialize_exported`` / ``deserialize_exported``: byte-level
  round-trip of a ``jax.export.Exported``. Serialization is
  deterministic for a fixed program + jaxlib (verified in
  tests/test_artifact_store.py), which is what makes the artifact
  store content-addressable and lets jax's persistent compile cache
  key stably on the deserialized module across processes.
- ``model_fingerprint``: sha256 of the serialized module bytes. The
  compiled program depends on the traced computation and the
  shapes/dtypes of its inputs — not on weight *values* (weights are
  runtime arguments) — so the module bytes are exactly the right
  identity for the artifact-store key.
- ``runtime_version``: the jax/jaxlib/backend triple an artifact is
  tied to. A deserialized module is only guaranteed loadable under a
  compatible runtime, so this string is part of the store key: a
  version skew is a clean store *miss* (recompile), never a crash.

A **bit-flipped export blob can deserialize and execute silently
wrong** (measured on jaxlib 0.4.37: the flatbuffer has no integrity
check of the embedded StableHLO payload) — which is why every consumer
of these bytes must verify a sha256 over them BEFORE deserializing.
The artifact store's MANIFEST does exactly that; ``jit.load`` trusts
local files the same way it always has.
"""
import hashlib


def serialize_exported(exported):
    """``jax.export.Exported`` -> bytes (the one on-disk format)."""
    return exported.serialize()


def deserialize_exported(blob):
    """bytes -> ``jax.export.Exported``. Raises on any malformed or
    version-incompatible payload — callers that cannot tolerate a
    raise (the artifact store load path) catch broadly and degrade."""
    from jax import export as jax_export

    return jax_export.deserialize(blob)


def canonical_module_bytes(exported):
    """Location-free identity bytes for a ``jax.export.Exported``.

    The serialized export embeds MLIR *debug locations* (``#locN``
    tables and inline ``loc(...)`` attributes) whose numbering depends
    on how many programs were traced earlier in the process — two
    byte-for-byte identical models can serialize differently depending
    on trace order (measured on jaxlib 0.4.37: the first trace in a
    process carries a smaller loc table than later ones). Anything that
    keys on *model identity* — the artifact store, the decode
    KV-snapshot header — must therefore hash the module with every
    location stripped, or a resume between two processes at different
    trace positions is refused as "foreign model" when it is not.

    Returns the pretty-printed StableHLO text with all ``loc``
    attributes and ``#loc`` definition lines removed, UTF-8 encoded.
    Computation structure, shapes, and dtypes are all still in the
    text, so distinct programs still hash apart."""
    out = []
    for line in exported.mlir_module().splitlines():
        if line.lstrip().startswith("#loc"):
            continue
        out.append(_strip_locs(line))
    return "\n".join(out).encode("utf-8")


def _strip_locs(line):
    """Remove every balanced ``loc(...)`` attribute from one line of
    MLIR text (quote-aware: parens inside string literals don't
    count)."""
    res = []
    i, n = 0, len(line)
    while i < n:
        j = line.find("loc(", i)
        # only a real loc attribute when at start or after a delimiter
        while j > 0 and line[j - 1] not in " (,=":
            j = line.find("loc(", j + 1)
        if j == -1:
            res.append(line[i:])
            break
        res.append(line[i:j].rstrip())
        k, depth, in_str = j + 4, 1, False
        while k < n and depth:
            c = line[k]
            if in_str:
                if c == "\\":
                    k += 1
                elif c == '"':
                    in_str = False
            elif c == '"':
                in_str = True
            elif c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            k += 1
        i = k
    return "".join(res)


def model_fingerprint(module_bytes, quant=None):
    """Content identity of a saved model: sha256 hex over its
    serialized exported-module bytes.

    ``quant`` (a serving quant mode: ``"w8"`` / ``"w8a8"`` /
    ``"bf16w"``) folds into the hash, so a quantized export is a
    DISTINCT artifact-store identity even in the degenerate case where
    two modes lower to byte-identical modules — a w8 program can never
    be served to an f32 request (or vice versa) on fingerprint grounds
    alone. ``None`` and the explicit ``"f32"`` spelling both keep the
    historical hash: every existing store and saved model keys
    identically regardless of which f32 spelling a caller uses."""
    h = hashlib.sha256(module_bytes)
    if quant is not None and quant != "f32":
        h.update(b"\x00quant:" + str(quant).encode("utf-8"))
    return h.hexdigest()


def runtime_version(backend=None):
    """The runtime an exported artifact is tied to, as one stable
    string: ``jax-<ver>/jaxlib-<ver>/<platform>``. Part of the
    artifact-store key, so artifacts written by a different runtime
    are simply never found (a miss, not a corruption)."""
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "unknown")
    except Exception:  # noqa: BLE001 - jaxlib may not expose a version
        jl = "unknown"
    if backend is None:
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - no backend yet: still keyable
            backend = "unknown"
    return f"jax-{jax.__version__}/jaxlib-{jl}/{backend}"
