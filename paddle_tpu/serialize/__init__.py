"""paddle_tpu.serialize — one serialization format for compiled programs.

Two cooperating pieces:

- ``export``: thin, shared helpers over ``jax.export`` — serialize /
  deserialize StableHLO modules, fingerprint a saved model, and name
  the runtime (jax + jaxlib + backend) a compiled artifact is tied to.
  ``jit.save`` / ``jit.load`` and the serving engine's per-bucket AOT
  programs all speak this one wire format now.
- ``artifact_store``: a crash-safe, content-addressed on-disk store of
  those serialized programs, keyed by (model fingerprint, bucket,
  signature, mesh, runtime version), with the resilience guarantees
  the checkpoint store proved out: tmp-dir + ``os.replace`` atomic
  publish, per-artifact ``MANIFEST.json`` sha256 self-verification,
  verify-on-load with quarantine + fallback, multi-process
  single-flight compile dedup, and retention GC. A fresh serving
  replica warms its bucket ladder from the store instead of paying
  multi-second XLA compiles — and a corrupt, torn, stale, or
  version-skewed artifact can never take it down (README "Artifact
  store" has the degradation matrix).
"""
from . import artifact_store  # noqa: F401
from . import export  # noqa: F401
from .artifact_store import (  # noqa: F401
    ArtifactKey,
    ArtifactStore,
    default_store,
)
from .export import (  # noqa: F401
    canonical_module_bytes,
    deserialize_exported,
    model_fingerprint,
    runtime_version,
    serialize_exported,
)

__all__ = [
    "artifact_store", "export",
    "ArtifactKey", "ArtifactStore", "default_store",
    "serialize_exported", "deserialize_exported",
    "canonical_module_bytes", "model_fingerprint", "runtime_version",
]
