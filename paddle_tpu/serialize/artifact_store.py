"""Crash-safe, content-addressed store of exported compiled programs.

Every fresh server process, hot reload, and elastic-resume attempt used
to re-pay multi-second XLA compiles because the ``.jax_compile_cache``,
the serving engine's per-bucket AOT cache, and ``jit.save``'s
polymorphic export were three disconnected mechanisms. This store
unifies them: a serving replica publishes each bucket's exported
program (``paddle_tpu.serialize.export``, the one wire format) under a
key that names everything the program depends on, and any later
process — a restarted replica, a scaled-out fleet, a hot reload —
loads it back instead of compiling.

A shared on-disk cache is only a win if a bad artifact can **never**
take a replica down, so every failure path degrades to an inline
compile (exactly what a replica with no store would do):

    failure mode                    behaviour
    ------------------------------  ---------------------------------
    artifact absent                 miss -> inline compile + publish
    bit-flipped / truncated payload sha256 verify fails -> quarantine
                                    (counter, never retried in-process,
                                    dir GC'd) -> inline compile
    torn publish (writer SIGKILL'd) never visible: publish is tmp-dir
                                    + os.replace; stale tmp GC'd
    version-skewed runtime          different key -> clean miss
    wrong-keyed / copied dir        manifest key check fails ->
                                    quarantine -> inline compile
    undeserializable payload        caller quarantines via
                                    ``quarantine()`` -> inline compile
    store dir unwritable            put() returns False (counter),
                                    serving continues store-less
    peer compiling same key         single-flight: wait for its
                                    publish (warmup) or compile inline
                                    without publishing (hot path)
    peer died holding the lock      staleness takeover (dead pid or
                                    age > stale_s; counter)

Key schema (``ArtifactKey`` -> sha256 digest -> ``art-<digest>/``)::

    model      sha256 of the saved model's serialized module bytes
               (weights are runtime args: same architecture = same key)
    bucket     batch rows the program was compiled for
    signature  ((dtype, trailing shape), ...) of the inputs
    mesh       device-mesh identity ("single" for one-chip serving)
    version    jax/jaxlib/backend triple (serialize.export
               .runtime_version) — artifacts never cross runtimes
    quant      serving quantization mode ("f32" default, omitted from
               the canonical form so historical digests are stable;
               "w8" / "w8a8" / "bf16w") — a quant-mode skew is a
               clean miss, a w8 program is never served to an f32
               request

On-disk layout (mirrors resilience/checkpoint.py, which proved the
pattern)::

    <root>/
      art-<digest>/
        MANIFEST.json        {"format":1,"key":{...},"sha256":...,
                              "size":N,"ts":...}
        program.jaxexport    serialized jax.export module
      .tmp-<digest>-<pid>-<n>/   in-flight publish; never read
      .lock-<digest>             O_EXCL single-flight compile lock

Concurrency: multi-process safe by construction (atomic renames, O_EXCL
locks); in-process the only shared mutable state is the quarantine set,
guarded by one leaf lock that nothing blocking runs under. The
single-flight wait loop sleeps OUTSIDE any lock.

Env knobs (README "Artifact store"):
    PADDLE_TPU_ARTIFACT_DIR        store root; unset = store disabled
                                   (default_store() returns None)
    PADDLE_TPU_ARTIFACT_MAX_BYTES  retention budget (default 2 GiB)
    PADDLE_TPU_ARTIFACT_MAX_COUNT  retention budget (default 512)
    PADDLE_TPU_ARTIFACT_DISABLE    "1" = kill switch, wins over
                                   everything (even explicit stores)
    PADDLE_TPU_ARTIFACT_STALE_S    lock/tmp staleness horizon
                                   (default 600s; XLA compiles can
                                   legitimately take minutes)

Chaos sites: ``artifact.get``, ``artifact.verify``, ``artifact.put``,
``artifact.put.publish`` (between payload write and the os.replace —
SIGKILL here models a torn publish).
"""
import hashlib
import json
import os
import shutil
import socket
import time
import threading
import warnings

from ..obs import metrics as _obs
from ..resilience import chaos
from ..resilience.checkpoint import _fsync_dir
from .export import runtime_version

FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
PAYLOAD_NAME = "program.jaxexport"

_HITS = _obs.counter(
    "paddle_artifact_hits_total",
    "Artifact-store loads that verified and were served")
_MISSES = _obs.counter(
    "paddle_artifact_misses_total",
    "Artifact-store lookups that found nothing usable")
_CORRUPT = _obs.counter(
    "paddle_artifact_corrupt_total",
    "Artifacts that failed verification and were quarantined")
_TAKEOVERS = _obs.counter(
    "paddle_artifact_takeovers_total",
    "Stale single-flight locks taken over from a dead/wedged peer")
_PUBLISHES = _obs.counter(
    "paddle_artifact_publishes_total", "Artifacts published")
_PUT_ERRORS = _obs.counter(
    "paddle_artifact_put_errors_total",
    "Failed publishes (swallowed: a bad store never fails serving)")
_GET_SECONDS = _obs.histogram(
    "paddle_artifact_get_seconds",
    "Store lookup latency by outcome (hit | miss)",
    labelnames=("outcome",),
    buckets=_obs.log_buckets(0.0001, 4.0, 10))
_PUT_SECONDS = _obs.histogram(
    "paddle_artifact_put_seconds", "Store publish latency",
    buckets=_obs.log_buckets(0.0001, 4.0, 10))


def _env_truthy(name):
    return os.environ.get(name, "0") not in ("", "0", "false", "False")


def disabled():
    """Operator kill switch: PADDLE_TPU_ARTIFACT_DISABLE=1 turns the
    store off everywhere, including engines handed an explicit store —
    the escape hatch that makes "can never be worse than no cache"
    recoverable in one env var even if a bug slips through."""
    return _env_truthy("PADDLE_TPU_ARTIFACT_DISABLE")


def default_store():
    """The process-default store, or None. Opt-in by env: the store
    activates only when PADDLE_TPU_ARTIFACT_DIR names a root (mirroring
    how the jax compile cache is enabled), so test suites and one-off
    scripts stay hermetic by default."""
    if disabled():
        return None
    root = os.environ.get("PADDLE_TPU_ARTIFACT_DIR")
    if not root:
        return None
    try:
        return ArtifactStore(root)
    except Exception as e:  # noqa: BLE001 - a bad store must not break startup
        warnings.warn(f"artifact store at {root!r} unusable ({e}); "
                      "serving continues without it")
        return None


class ArtifactKey:
    """Everything a compiled program's identity depends on. Weights are
    runtime arguments, so they are deliberately NOT part of the key —
    a re-save of the same architecture with new weights reuses the
    same artifacts.

    ``quant`` names the serving quantization mode the program was
    exported under (``"f32"`` default; ``"w8"`` / ``"w8a8"`` /
    ``"bf16w"``). The model fingerprint already folds the mode in
    (serialize.export.model_fingerprint), but the key carries it
    EXPLICITLY as well: a quant-mode skew is a clean miss by key
    construction — a w8 artifact can never be served to an f32 request
    even if the fingerprints were ever to collide — and the manifest's
    recorded key makes the mode auditable on disk. ``"f32"`` is
    omitted from the canonical form so every pre-quantization digest
    (and on-disk manifest) stays byte-identical."""

    __slots__ = ("model", "bucket", "signature", "mesh", "version",
                 "quant")

    def __init__(self, model, bucket, signature, mesh="single",
                 version=None, quant=None):
        self.model = str(model)
        self.bucket = int(bucket)
        # normalize to ((dtype_str, (trailing...)), ...) so logically
        # equal signatures always digest identically
        self.signature = tuple((str(dt), tuple(int(d) for d in tr))
                               for dt, tr in signature)
        self.mesh = str(mesh)
        self.version = runtime_version() if version is None else str(version)
        self.quant = "f32" if quant in (None, "f32") else str(quant)

    def canonical(self):
        """JSON-able identity — what the digest hashes and what the
        manifest records for self-verification."""
        c = {"model": self.model, "bucket": self.bucket,
             "signature": [[dt, list(tr)] for dt, tr in self.signature],
             "mesh": self.mesh, "version": self.version}
        if self.quant != "f32":
            c["quant"] = self.quant
        return c

    def digest(self):
        blob = json.dumps(self.canonical(), sort_keys=True)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:32]

    def __repr__(self):
        return (f"ArtifactKey(model={self.model[:12]}..., "
                f"bucket={self.bucket}, mesh={self.mesh})")


class _FlightLock:
    """A held single-flight lock: the lockfile path plus the token that
    proves ownership (release only unlinks a lock that still carries
    our token, so a stale-lock takeover victim that resurrects cannot
    delete the taker's lock)."""

    __slots__ = ("digest", "path", "token")

    def __init__(self, digest, path, token):
        self.digest = digest
        self.path = path
        self.token = token


class ArtifactStore:
    """Atomic publish / verified load / single-flight / retention GC
    over one directory (multi-process shared; typically a persistent
    volume all replicas mount)."""

    def __init__(self, root, max_bytes=None, max_count=None,
                 stale_s=None, poll_interval=0.05, gc_grace_s=None):
        self.root = os.path.abspath(root)
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else os.environ.get("PADDLE_TPU_ARTIFACT_MAX_BYTES",
                                2 * 1024 ** 3))
        self.max_count = int(
            max_count if max_count is not None
            else os.environ.get("PADDLE_TPU_ARTIFACT_MAX_COUNT", 512))
        self.stale_s = float(
            stale_s if stale_s is not None
            else os.environ.get("PADDLE_TPU_ARTIFACT_STALE_S", 600.0))
        self.poll_interval = float(poll_interval)
        # retention never evicts an artifact younger than this: a
        # just-published program is exactly what warming peers are
        # about to read, and a budget filled with locked (mid-publish)
        # entries must not force the NEWEST artifact out — running
        # temporarily over budget is the lesser harm
        self.gc_grace_s = float(min(60.0, self.stale_s)
                                if gc_grace_s is None else gc_grace_s)
        self._host = socket.gethostname()
        self._lock = threading.Lock()  # leaf: guards the mutable dicts only
        self._quarantined = {}  # digest -> reason (never retried in-process)
        self._seq = 0
        # per-INSTANCE counters for stats()/health: the module-level obs
        # instruments are process-global (right for the exposition), but
        # a health block claiming to describe THIS store must not sum in
        # another store's traffic (two served models, or the old+new
        # engine pair during a hot-reload window)
        self._local = {"hits": 0, "misses": 0, "corrupt": 0,
                       "takeovers": 0, "publishes": 0, "put_errors": 0}
        # stats() caches its directory walk: health probes poll, and a
        # full per-artifact listdir+getsize against a shared volume on
        # every poll is pure metadata load. Local mutations invalidate;
        # cross-process changes surface within stats_ttl_s.
        self.stats_ttl_s = 5.0
        self._entries_cache = (0.0, None)
        os.makedirs(self.root, exist_ok=True)

    def _bump(self, name):
        with self._lock:
            self._local[name] += 1

    def _invalidate_entries_cache(self):
        with self._lock:
            self._entries_cache = (0.0, None)

    # --------------------------------------------------------------- paths
    def _final(self, digest):
        return os.path.join(self.root, f"art-{digest}")

    def _lockfile(self, digest):
        return os.path.join(self.root, f".lock-{digest}")

    def _next_tmp(self, digest):
        with self._lock:
            self._seq += 1
            seq = self._seq
        return os.path.join(self.root,
                            f".tmp-{digest}-{os.getpid()}-{seq}")

    # ----------------------------------------------------------------- get
    def get(self, key):
        """Verified payload bytes for `key`, or None (absent, corrupt,
        or quarantined — the caller compiles inline either way). A
        corrupt artifact is quarantined: counted, deleted, and never
        retried by this process. NEVER raises: an I/O blow-up reading
        the store (chaos-tested via the ``artifact.get`` site) is a
        miss, not a serving failure."""
        t0 = time.perf_counter()
        try:
            payload = self._read_verified(key)
        except Exception as e:  # noqa: BLE001 - a broken store = a miss
            warnings.warn(f"artifact store read failed ({e}); "
                          "treating as a miss")
            payload = None
        outcome = "miss" if payload is None else "hit"
        (_MISSES if payload is None else _HITS).inc()
        self._bump("misses" if payload is None else "hits")
        _GET_SECONDS.observe(time.perf_counter() - t0, outcome=outcome)
        return payload

    def _read_verified(self, key):
        """get() without the counters (the single-flight wait loop
        polls this; its final outcome is counted once by the caller).
        Corruption is ALWAYS counted + quarantined — that is real
        signal, not polling noise. The chaos site lives here so
        injected read failures cover BOTH the direct get() path and
        the single-flight wait loop (each degrades independently)."""
        chaos.hit("artifact.get")
        digest = key.digest()
        with self._lock:
            if digest in self._quarantined:
                return None
        final = self._final(digest)
        manifest_path = os.path.join(final, MANIFEST_NAME)
        if not os.path.isfile(manifest_path):
            return None
        try:
            payload = self._verify(key, final)
        except OSError as e:
            # A read error is NOT corruption: a shared-volume hiccup
            # (ESTALE/EIO) or a peer's concurrent evict must never
            # make one replica destroy a possibly-good artifact for
            # the whole fleet — that's a miss. The one structural
            # exception: the manifest is still there but the payload
            # is not, a state no store operation can produce (publish
            # and evict are whole-dir-atomic), so it IS corruption.
            if (isinstance(e, FileNotFoundError)
                    and os.path.isfile(manifest_path)
                    and not os.path.isfile(
                        os.path.join(final, PAYLOAD_NAME))):
                self.quarantine(key, f"payload file missing: {e}")
            return None
        except Exception as e:  # noqa: BLE001 - any bad artifact degrades
            self.quarantine(key, str(e))
            return None
        try:
            # LRU signal for retention GC (never load-bearing)
            os.utime(final)
        except OSError:
            pass
        return payload

    def _verify(self, key, final):
        """Manifest + payload verification; returns the payload bytes
        or raises. Everything get() trusts is checked here: manifest
        format, the full key (a renamed/copied dir fails even though
        its digest directory matched), payload size and sha256."""
        chaos.hit("artifact.verify")
        with open(os.path.join(final, MANIFEST_NAME)) as f:
            manifest = json.load(f)
        if manifest.get("format") != FORMAT_VERSION:
            raise ValueError(
                f"unknown manifest format {manifest.get('format')!r}")
        if manifest.get("key") != key.canonical():
            raise ValueError("manifest key mismatch (wrong-keyed or "
                             "copied artifact dir)")
        with open(os.path.join(final, PAYLOAD_NAME), "rb") as f:
            payload = f.read()
        if len(payload) != int(manifest.get("size", -1)):
            raise ValueError(
                f"payload size {len(payload)} != manifest "
                f"{manifest.get('size')}")
        sha = hashlib.sha256(payload).hexdigest()
        if sha != manifest.get("sha256"):
            raise ValueError("payload sha256 mismatch (bit rot or torn "
                             "write)")
        return payload

    # ---------------------------------------------------------- quarantine
    def quarantine(self, key, reason):
        """Mark `key` bad: counted, never retried in-process, and its
        directory removed (atomically renamed aside first, so a
        concurrent reader sees the artifact or nothing — never half a
        deletion). Callers use this for failures the store itself
        cannot see, e.g. a payload that verified byte-wise but does
        not deserialize under this runtime."""
        digest = key.digest()
        with self._lock:
            already = digest in self._quarantined
            self._quarantined[digest] = str(reason)
        if already:
            return
        _CORRUPT.inc()
        self._bump("corrupt")
        warnings.warn(
            f"artifact {digest} quarantined ({reason}); degrading to "
            "inline compile")
        final = self._final(digest)
        aside = os.path.join(self.root,
                             f".bad-{digest}-{os.getpid()}")
        try:
            os.replace(final, aside)
        except OSError:
            return  # already gone (another process quarantined it)
        shutil.rmtree(aside, ignore_errors=True)

    def is_quarantined(self, key):
        with self._lock:
            return key.digest() in self._quarantined

    # ----------------------------------------------------------------- put
    def put(self, key, payload):
        """Publish atomically. Returns True when the artifact is live
        (published by us or already present), False on any failure —
        put NEVER raises: a broken store degrades serving to
        compile-only, it does not take the replica down."""
        t0 = time.perf_counter()
        try:
            chaos.hit("artifact.put")
            if disabled():
                return False
            outcome = self._put_raising(key, bytes(payload))
        except Exception as e:  # noqa: BLE001 - publish is best-effort
            _PUT_ERRORS.inc()
            self._bump("put_errors")
            warnings.warn(f"artifact publish failed ({e}); serving "
                          "continues without it")
            return False
        if outcome == "wrote":
            # counted only when WE materialized the artifact — "a peer
            # beat us to it" must not inflate the publish metric, or it
            # could no longer witness the one-publish-per-key contract
            _PUBLISHES.inc()
            self._bump("publishes")
            _PUT_SECONDS.observe(time.perf_counter() - t0)
            self._invalidate_entries_cache()
        return bool(outcome)

    # Staging-dir lifecycle: every tmp dir comes from _tmp_create and
    # ends in exactly one _tmp_done (publish moves it aside first, so
    # the rmtree is then a no-op on the corpse name). The TPU5xx lint
    # and the restrace sanitizer both key on this pair.
    # tpu-resource: acquires=tmp_dir
    def _tmp_create(self, digest):
        """Create one private staging dir (the tmp half of the
        write-then-rename publish); the owner must _tmp_done() it on
        every path, or gc() only reclaims it by age."""
        os.makedirs(self.root, exist_ok=True)
        tmp = self._next_tmp(digest)
        os.makedirs(tmp)
        return tmp

    # tpu-resource: releases=tmp_dir
    def _tmp_done(self, tmp):
        """Retire a staging dir, published or abandoned."""
        shutil.rmtree(tmp, ignore_errors=True)

    def _put_raising(self, key, payload):
        """-> "wrote" (we published it) | "present" (a peer already
        had) — both truthy "the artifact is live" outcomes."""
        digest = key.digest()
        final = self._final(digest)
        if os.path.isdir(final):
            return "present"  # content-addressed: a peer already published
        tmp = self._tmp_create(digest)
        try:
            with open(os.path.join(tmp, PAYLOAD_NAME), "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            manifest = {"format": FORMAT_VERSION,
                        "key": key.canonical(),
                        "sha256": hashlib.sha256(payload).hexdigest(),
                        "size": len(payload),
                        "ts": time.time()}
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            # SIGKILL between here and the replace = a torn publish:
            # the final dir never appears, the tmp dir is GC'd by age
            chaos.hit("artifact.put.publish")
            try:
                os.replace(tmp, final)
            except OSError:
                if os.path.isdir(final):
                    return "present"  # lost the publish race: it exists
                raise
        finally:
            self._tmp_done(tmp)
        _fsync_dir(self.root)
        self.gc()
        return "wrote"

    # -------------------------------------------------------- single-flight
    # tpu-resource: acquires=flight_lock
    def try_acquire(self, key):
        """Non-blocking single-flight claim for compiling `key`.
        Returns a _FlightLock when this caller owns the compile+publish
        (release() it when done), None when a peer holds it — the hot
        path then compiles inline WITHOUT publishing (never waits on a
        peer while live traffic is parked)."""
        digest = key.digest()
        path = self._lockfile(digest)
        token = f"{self._host}:{os.getpid()}:{time.monotonic_ns()}"
        body = json.dumps({"pid": os.getpid(), "host": self._host,
                           "ts": time.time(), "token": token})
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return None
        except OSError:
            return None  # unwritable store: behave as "peer holds it"
        try:
            os.write(fd, body.encode("utf-8"))
            os.fsync(fd)
        except OSError:
            # a bodyless lock is indistinguishable from a crashed
            # writer's corpse: peers would declare it stale within
            # seconds and take it over mid-compile, silently breaking
            # the one-compile-per-bucket contract exactly when the
            # store disk is degraded. Better to hold no lock at all
            # (compile inline, skip publishing).
            os.close(fd)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        os.close(fd)
        return _FlightLock(digest, path, token)

    # tpu-resource: releases=flight_lock
    def release(self, lock):
        """Drop a held lock. Only unlinks the file if it still carries
        our token — a takeover may have replaced it."""
        if lock is None:
            return
        try:
            with open(lock.path) as f:
                body = json.load(f)
            if body.get("token") != lock.token:
                return
        except (OSError, json.JSONDecodeError):
            return
        try:
            os.unlink(lock.path)
        except OSError:
            pass

    def _lock_stale(self, path):
        """Is the lock at `path` held by a dead or wedged peer? Same-
        host dead pids are stale immediately (the SIGKILL-mid-publish
        case resolves in one poll); otherwise age decides."""
        try:
            st = os.stat(path)
            with open(path) as f:
                body = json.load(f)
        except (OSError, json.JSONDecodeError):
            # vanished (owner released: not stale) or unreadable
            # garbage (torn lock write: stale once old enough)
            try:
                st = os.stat(path)
            except OSError:
                return False
            return time.time() - st.st_mtime > max(5.0, self.poll_interval)
        if body.get("host") == self._host:
            pid = body.get("pid")
            if isinstance(pid, int):
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    return True  # owner is gone
                except OSError:
                    pass  # EPERM etc: assume alive
        age = time.time() - max(float(body.get("ts", 0.0)), st.st_mtime)
        return age > self.stale_s

    def _takeover(self, path):
        """Atomically remove a stale lock. The rename arbitrates:
        exactly one of N racing takers wins; losers just retry the
        acquire loop."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        dead = f"{path}.dead-{os.getpid()}-{seq}"
        try:
            os.rename(path, dead)
        except OSError:
            return False
        try:
            os.unlink(dead)
        except OSError:
            pass
        _TAKEOVERS.inc()
        self._bump("takeovers")
        return True

    # tpu-resource: acquires=flight_lock
    def acquire_or_wait(self, key, timeout=None):
        """Blocking single-flight for warmup: either WE own the compile
        (-> (lock, None)), or a peer published while we waited
        (-> (None, payload)), or the wait timed out (-> (None, None):
        compile inline, skip publishing — never wedge a warmup).

        A peer that dies holding the lock is taken over (counted) via
        pid-liveness on this host or the staleness horizon across
        hosts, so one SIGKILL'd replica never wedges the fleet.
        NEVER raises: any store blow-up resolves to (None, None) — the
        caller compiles inline, exactly as with no store."""
        try:
            return self._acquire_or_wait(key, timeout)
        except Exception as e:  # noqa: BLE001 - degrade to inline
            warnings.warn(f"artifact single-flight failed ({e}); "
                          "compiling inline without publish")
            return None, None

    # tpu-resource: acquires=flight_lock
    def _acquire_or_wait(self, key, timeout):
        # timeout=0 means "try once, never park" (an operator setting
        # PADDLE_TPU_ARTIFACT_WARMUP_WAIT_S=0 asked for exactly that);
        # only timeout=None waits indefinitely
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        counted_t0 = time.perf_counter()
        while True:
            lock = self.try_acquire(key)
            if lock is not None:
                # between our miss and the acquire a peer may have
                # published and released: serve that instead of
                # recompiling. A read blow-up here must not leak the
                # just-acquired lock (peers would stall until the
                # staleness horizon).
                try:
                    payload = self._read_verified(key)
                except Exception:
                    self.release(lock)
                    raise
                if payload is not None:
                    self.release(lock)
                    _HITS.inc()
                    self._bump("hits")
                    _GET_SECONDS.observe(time.perf_counter() - counted_t0,
                                         outcome="hit")
                    return None, payload
                return lock, None
            payload = self._read_verified(key)
            if payload is not None:
                _HITS.inc()
                self._bump("hits")
                _GET_SECONDS.observe(time.perf_counter() - counted_t0,
                                     outcome="hit")
                return None, payload
            lp = self._lockfile(key.digest())
            if os.path.exists(lp) and self._lock_stale(lp):
                self._takeover(lp)
                continue  # retry the acquire immediately
            if deadline is not None and time.monotonic() >= deadline:
                _MISSES.inc()
                self._bump("misses")
                _GET_SECONDS.observe(time.perf_counter() - counted_t0,
                                     outcome="miss")
                return None, None
            time.sleep(self.poll_interval)

    # ------------------------------------------------------------------ gc
    def _entries(self):
        """[(mtime, bytes, digest, path)] for every published artifact."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for n in names:
            if not n.startswith("art-"):
                continue
            full = os.path.join(self.root, n)
            try:
                size = sum(
                    os.path.getsize(os.path.join(full, fn))
                    for fn in os.listdir(full))
                out.append((os.path.getmtime(full), size, n[4:], full))
            except OSError:
                continue  # vanished mid-scan (concurrent GC/quarantine)
        return out

    def gc(self):
        """Retention: evict oldest artifacts past the count/byte
        budgets, plus crashed publishers' stale leftovers. Never
        raises; never touches an artifact whose single-flight lock is
        live (a peer is mid-publish on it), never touches a FRESH tmp
        dir (an in-flight write)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        now = time.time()
        for n in names:
            full = os.path.join(self.root, n)
            if n.startswith(".bad-") or n.startswith(".evict-"):
                shutil.rmtree(full, ignore_errors=True)
            elif n.startswith(".tmp-"):
                try:
                    if now - os.path.getmtime(full) > self.stale_s:
                        shutil.rmtree(full, ignore_errors=True)
                except OSError:
                    pass
            elif n.startswith(".lock-") and ".dead-" in n:
                # a takeover that crashed between its rename and unlink
                # left this corpse; by construction nothing reads it
                try:
                    os.unlink(full)
                except OSError:
                    pass
            elif n.startswith(".lock-"):
                if self._lock_stale(full):
                    self._takeover(full)
        entries = sorted(self._entries())  # oldest first
        total = sum(e[1] for e in entries)
        count = len(entries)
        for mtime, size, digest, path in entries:
            over = ((self.max_count > 0 and count > self.max_count)
                    or (self.max_bytes > 0 and total > self.max_bytes))
            if not over:
                break
            if now - mtime < self.gc_grace_s:
                continue  # fresh publish: warming peers read it next
            lp = self._lockfile(digest)
            if os.path.exists(lp) and not self._lock_stale(lp):
                continue  # a peer is mid-publish/compile on this key
            # dot-prefixed aside: a crash between the replace and the
            # rmtree must leave something _entries() ignores and the
            # sweep above reclaims, not a phantom "live" artifact
            aside = os.path.join(
                self.root, f".evict-{digest}-{os.getpid()}")
            try:
                os.replace(path, aside)
            except OSError:
                continue  # already gone
            shutil.rmtree(aside, ignore_errors=True)
            total -= size
            count -= 1
            self._invalidate_entries_cache()

    # --------------------------------------------------------------- stats
    def _entries_cached(self):
        now = time.monotonic()
        with self._lock:
            ts, cached = self._entries_cache
            if cached is not None and now - ts < self.stats_ttl_s:
                return cached
        entries = self._entries()
        with self._lock:
            self._entries_cache = (now, entries)
        return entries

    def stats(self):
        """Per-store view for health probes: in-memory counters for
        THIS instance (the obs instruments stay process-global for the
        exposition) plus a TTL-cached directory census — a monitor
        polling health must not hammer the shared volume with a full
        per-artifact walk every few seconds."""
        entries = self._entries_cached()
        with self._lock:
            local = dict(self._local)
            quarantined = len(self._quarantined)
        local.update({
            "root": self.root,
            "artifacts": len(entries),
            "bytes": sum(e[1] for e in entries),
            "max_bytes": self.max_bytes,
            "max_count": self.max_count,
            "quarantined": quarantined,
        })
        return local
