"""paddle.metric (reference: python/paddle/metric/metrics.py)."""
import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._value if isinstance(label, Tensor) else label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        num = c.shape[0] if c.ndim > 0 else 1
        accs = []
        for k in self.topk:
            ck = c[..., :k].sum(-1)
            self.total[self.topk.index(k)] += float(ck.sum())
            self.count[self.topk.index(k)] += num
            accs.append(float(ck.sum()) / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds).reshape(-1)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds).reshape(-1)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None):
    """Functional accuracy (reference: fluid/layers/metric_op.py accuracy)."""
    pred_np = np.asarray(input._value)
    label_np = np.asarray(label._value)
    idx = np.argsort(-pred_np, axis=-1)[:, :k]
    if label_np.ndim == 2:
        label_np = label_np[:, 0]
    acc = float(np.mean(np.any(idx == label_np[:, None], axis=1)))
    return Tensor(np.asarray([acc], np.float32))


from ..core.module_alias import alias_submodules as _alias

_alias(__name__, "metrics")
