"""Compile ledger: a record of every XLA compile the process pays.

On TPU, unexpected recompiles are the dominant silent performance
regression (tracelint's TPU101–TPU104 catch them statically; the ledger
catches them at runtime), and compiled-program *structure* — op mix,
cost-analysis FLOPs/bytes — is a chip-independent proxy for the perf a
dead TPU tunnel can't measure (ROADMAP item 4). Every entry records:

    key          caller-chosen identity (e.g. "serving/bucket8")
    kind         "aot" (jax AOT .lower().compile()), "callable", ...
    duration_s   wall-clock compile time
    flops / bytes_accessed   from ``compiled.cost_analysis()``
    op_counts    {hlo_opcode: n} parsed from ``compiled.as_text()``
    fingerprint  sha256 over the ordered opcode sequence — a
                 *structural* HLO identity that ignores value names and
                 literal payloads, so two compiles of the same program
                 shape match even when buffer ids differ

``bench.py perfproxy`` replays a fixed scenario against this ledger and
diffs compile counts / op counts / FLOPs against a committed baseline
(PERFPROXY_BASELINE.json) — the CPU-only CI stand-in for the single-chip
speed ladder.
"""
import hashlib
import re
import threading
import time

from . import metrics as _metrics
from . import tracing as _tracing

_COMPILES = _metrics.counter(
    "paddle_compile_events_total",
    "XLA compile events recorded in the compile ledger",
    labelnames=("kind",))
_COMPILE_SECONDS = _metrics.histogram(
    "paddle_compile_seconds",
    "Duration of recorded compile events",
    buckets=_metrics.log_buckets(0.001, 4.0, 10))

_OPCODE_RE = re.compile(r"^[a-zA-Z][\w-]*")


def _strip_hlo_type(rhs):
    """Drop the leading result type from an HLO instruction RHS —
    either a whitespace-free shape like ``f32[8,4]{1,0}`` or a
    parenthesized tuple type like ``(f32[2]{0}, s32[])`` (which
    contains spaces, so token-splitting alone would mis-parse)."""
    rhs = rhs.lstrip()
    if rhs.startswith("("):
        depth = 0
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rhs[i + 1:].lstrip()
        return ""
    parts = rhs.split(None, 1)
    return parts[1] if len(parts) > 1 else ""


def hlo_typed_opcodes(hlo_text):
    """Ordered ``opcode:result_dtype`` sequence of every instruction in
    an HLO module text dump (computation headers and metadata lines are
    skipped) — ``convert:f32``, ``parameter:s8``, ``dot:f32``;
    tuple-typed results report ``tuple``. The ONE parsing pass: the
    untyped view is a projection (:func:`hlo_opcodes`). The dtype
    dimension is what the quant-ladder perfproxy section gates on: a
    ``parameter:s8`` / ``parameter:bf16`` count proves
    reduced-precision weights actually reached XLA instead of silently
    promoting to f32 upstream of the lowering."""
    ops = []
    for line in hlo_text.splitlines():
        if " = " not in line:
            continue
        rhs_full = line.split(" = ", 1)[1].lstrip()
        if rhs_full.startswith("("):
            dtype = "tuple"
        else:
            head = rhs_full.split(None, 1)[0]
            dtype = head.split("[", 1)[0]
        rhs = _strip_hlo_type(rhs_full)
        m = _OPCODE_RE.match(rhs)
        if m and "(" in rhs[m.end():m.end() + 1]:
            ops.append(f"{m.group(0)}:{dtype}")
    return ops


def hlo_opcodes(hlo_text):
    """Ordered opcode sequence of every instruction in an HLO module
    text dump — the dtype-less projection of
    :func:`hlo_typed_opcodes` (opcode names never contain ``:``), so
    there is exactly one parser to maintain."""
    return [op.partition(":")[0] for op in hlo_typed_opcodes(hlo_text)]


def hlo_fingerprint(opcodes):
    """Structural identity: sha256 over the ordered opcode sequence."""
    h = hashlib.sha256()
    for op in opcodes:
        h.update(op.encode("ascii", "replace"))
        h.update(b"\n")
    return h.hexdigest()[:16]


def analyze_compiled(compiled):
    """Best-effort structural + cost analysis of a jax AOT ``Compiled``.

    Never raises: backends without as_text()/cost_analysis() yield a
    partial record (the ledger must not break serving when XLA's
    introspection surface shifts under a jax upgrade)."""
    out = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            flops = cost.get("flops")
            if flops is not None:
                out["flops"] = float(flops)
            acc = cost.get("bytes accessed")
            if acc is not None:
                out["bytes_accessed"] = float(acc)
    except Exception:  # noqa: BLE001 — introspection is best-effort
        pass
    try:
        # ONE parse of the HLO text; the untyped view (op_counts,
        # n_ops, the structural fingerprint — all byte-compatible with
        # pre-quant baselines) is a projection of the typed sequence
        typed_ops = hlo_typed_opcodes(compiled.as_text())
        ops = [op.partition(":")[0] for op in typed_ops]
        counts = {}
        for op in ops:
            counts[op] = counts.get(op, 0) + 1
        out["op_counts"] = counts
        out["n_ops"] = len(ops)
        out["fingerprint"] = hlo_fingerprint(ops)
        typed = {}
        for op in typed_ops:
            typed[op] = typed.get(op, 0) + 1
        # opcode:result_dtype counts — the reduced-precision evidence
        # (parameter:s8 / parameter:bf16 / convert:f32) the quant
        # perfproxy section diffs; untyped totals stay the gate for
        # everything else
        out["typed_op_counts"] = typed
    except Exception:  # noqa: BLE001
        pass
    return out


class CompileLedger:
    """Append-only, bounded record of compile events."""

    def __init__(self, cap=1024):
        self._lock = threading.Lock()
        self._events = []
        self._cap = cap

    def record(self, key, duration_s=None, compiled=None, kind="aot",
               extra=None):
        """Record one compile event; returns the event dict."""
        ev = {"key": str(key), "kind": kind, "ts": time.time()}
        if duration_s is not None:
            ev["duration_s"] = round(float(duration_s), 6)
        if compiled is not None:
            ev.update(analyze_compiled(compiled))
        if extra:
            ev.update(extra)
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._cap:
                del self._events[:len(self._events) - self._cap]
        _COMPILES.inc(kind=kind)
        if duration_s is not None:
            _COMPILE_SECONDS.observe(float(duration_s))
            _tracing.observe(f"compile:{key}", float(duration_s))
        return ev

    def events(self, key_prefix=None):
        with self._lock:
            evs = list(self._events)
        if key_prefix is not None:
            evs = [e for e in evs if e["key"].startswith(key_prefix)]
        return evs

    def totals(self, key_prefix=None):
        """Aggregate view the perf-proxy gate diffs: compile count,
        summed flops/bytes, merged op counts."""
        evs = self.events(key_prefix)
        op_counts = {}
        flops = 0.0
        acc = 0.0
        for e in evs:
            flops += e.get("flops", 0.0)
            acc += e.get("bytes_accessed", 0.0)
            for op, n in e.get("op_counts", {}).items():
                op_counts[op] = op_counts.get(op, 0) + n
        return {"compiles": len(evs), "flops": flops,
                "bytes_accessed": acc, "op_counts": op_counts,
                "n_ops": sum(op_counts.values())}

    def reset(self):
        with self._lock:
            self._events = []


#: Default process ledger (the serving engine's AOT compiles land here).
LEDGER = CompileLedger()
