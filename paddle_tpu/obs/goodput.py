"""Goodput accounting: useful step time vs. everything else.

The fleet papers ("ML Productivity Goodput", PAPERS.md) frame the
production training metric not as step throughput but as the fraction
of wall-clock spent making forward progress — checkpoints, retries,
rollbacks, and idle waits are all throughput a preemption-prone fleet
silently loses. ROADMAP item 3 reduces to this ledger.

Categories:

    step        a useful training step (the numerator)
    checkpoint  save/restore I/O (resilience/checkpoint.py feeds this)
    retry       backoff sleeps (resilience/retry.py feeds this)
    rollback    bad-step checkpoint restores (resilience/badstep.py)
    serving     reply-seconds spent on in-deadline OK replies (the
                fleet router feeds this; ServingGoodput below holds the
                per-tenant breakdown)
    idle        wall-clock not covered by any recorded category

Use either the context managers::

    acct = goodput.ACCOUNTANT
    with acct.step():        loss = train_step(...)
    with acct.checkpoint():  manager.save(state, step)

or feed pre-measured durations with ``account(category, seconds)`` —
the resilience hooks do the latter so instrumentation never changes
control flow. ``report()`` yields the goodput fraction; the same
numbers are exported as ``paddle_goodput_seconds_total{category=...}``
through the default metrics registry.
"""
import contextlib
import threading
import time

from . import metrics as _metrics

CATEGORIES = ("step", "checkpoint", "retry", "rollback", "serving", "idle")

_SECONDS = _metrics.counter(
    "paddle_goodput_seconds_total",
    "Wall-clock seconds per goodput category (step = useful time)",
    labelnames=("category",))
_EVENTS = _metrics.counter(
    "paddle_goodput_events_total",
    "Recorded goodput events per category",
    labelnames=("category",))


class GoodputAccountant:
    """Thread-safe per-category time ledger.

    Wall-clock (for the idle residual) runs from the first recorded
    event to the last; a quiet accountant reports goodput 0.0 rather
    than inventing a denominator.
    """

    def __init__(self, export=True):
        self._lock = threading.Lock()
        self._totals = {c: 0.0 for c in CATEGORIES}
        self._counts = {c: 0 for c in CATEGORIES}
        self._t_first = None
        self._t_last = None
        self._export = export

    def account(self, category, seconds):
        if category not in CATEGORIES:
            raise ValueError(
                f"unknown goodput category {category!r} "
                f"(have {CATEGORIES})")
        seconds = max(0.0, float(seconds))
        now = time.monotonic()
        with self._lock:
            self._totals[category] += seconds
            self._counts[category] += 1
            if self._t_first is None:
                self._t_first = now - seconds
            self._t_last = now
        if self._export:
            _SECONDS.inc(seconds, category=category)
            _EVENTS.inc(category=category)

    @contextlib.contextmanager
    def _timed(self, category):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.account(category, time.perf_counter() - t0)

    def step(self):
        return self._timed("step")

    def checkpoint(self):
        return self._timed("checkpoint")

    def retry(self):
        return self._timed("retry")

    def rollback(self):
        return self._timed("rollback")

    def report(self):
        """-> {<cat>_s, steps, total_s, goodput}. ``idle_s`` is the
        first-to-last-event wall-clock not covered by any recorded
        category (plus anything accounted explicitly as idle)."""
        with self._lock:
            totals = dict(self._totals)
            steps = self._counts["step"]
            wall = ((self._t_last - self._t_first)
                    if self._t_first is not None else 0.0)
        accounted = sum(totals.values())
        idle = totals["idle"] + max(0.0, wall - accounted)
        total = max(wall, accounted)
        out = {f"{c}_s": round(totals[c], 6) for c in CATEGORIES}
        out["idle_s"] = round(idle, 6)
        out["steps"] = steps
        out["total_s"] = round(total, 6)
        out["goodput"] = round(totals["step"] / total, 6) if total else 0.0
        # the fleet-facing rate (bench.py goodput): useful steps per
        # wall-clock hour, the "ML Productivity Goodput" numerator
        out["steps_per_hour"] = (round(steps / total * 3600.0, 3)
                                 if total else 0.0)
        return out

    def reset(self):
        with self._lock:
            self._totals = {c: 0.0 for c in CATEGORIES}
            self._counts = {c: 0 for c in CATEGORIES}
            self._t_first = self._t_last = None


#: Default process accountant; the resilience runtime feeds it.
ACCOUNTANT = GoodputAccountant()


def account(category, seconds):
    ACCOUNTANT.account(category, seconds)


def report():
    return ACCOUNTANT.report()


# --------------------------------------------------------------- serving
# Reply outcomes the fleet router records. "ok" is the goodput
# numerator: the reply arrived AND met its deadline (or carried none).
SERVING_OUTCOMES = ("ok", "late", "shed", "error")

_SERVING_SECONDS = _metrics.counter(
    "paddle_serving_goodput_seconds_total",
    "Reply-service seconds per tenant and outcome (ok = in-deadline)",
    labelnames=("tenant", "outcome"))
_SERVING_REPLIES = _metrics.counter(
    "paddle_serving_replies_total",
    "Fleet replies per tenant and outcome",
    labelnames=("tenant", "outcome"))
_SERVING_TOKENS = _metrics.counter(
    "paddle_serving_goodput_tokens_total",
    "Streamed tokens per tenant and outcome (the token-streaming "
    "workload's goodput unit: a reply is many tokens, so tenant SLO "
    "accounting must count tokens, not replies)",
    labelnames=("tenant", "outcome"))


class ServingGoodput:
    """Serving-side goodput ledger ("ML Productivity Goodput" applied
    to a reply fleet): the fraction of fleet reply-seconds spent on
    replies that met their deadline, broken down per tenant.

    The router records one event per finished request::

        SERVING_LEDGER.record("tenant-a", "ok", seconds=0.012)

    Streaming decode replies additionally carry their token count —
    the unit tenant SLO accounting uses for token workloads (one
    streamed reply is hundreds of tokens; counting replies would let
    a tenant's one giant stream look equal to another's one tiny
    one)::

        SERVING_LEDGER.record("tenant-a", "ok", seconds=1.2, tokens=128)

    ``report()`` gives the fleet goodput fraction plus per-tenant
    reply/deadline-hit counts and token totals (``goodput_tokens`` =
    in-SLO tokens over all streamed tokens); the same numbers export
    as ``paddle_serving_goodput_seconds_total{tenant,outcome}`` /
    ``paddle_serving_replies_total{tenant,outcome}`` /
    ``paddle_serving_goodput_tokens_total{tenant,outcome}``. Every
    in-deadline OK reply's service time is also fed to the process
    accountant's ``serving`` category, so one `goodput.report()` spans
    training and serving."""

    def __init__(self, export=True, accountant=None):
        self._lock = threading.Lock()
        self._data = {}  # tenant -> {outcome: [count, seconds, tokens]}
        self._export = export
        self._accountant = accountant

    def record(self, tenant, outcome, seconds=0.0, tokens=0):
        if outcome not in SERVING_OUTCOMES:
            raise ValueError(f"unknown serving outcome {outcome!r} "
                             f"(have {SERVING_OUTCOMES})")
        tenant = str(tenant)
        seconds = max(0.0, float(seconds))
        tokens = max(0, int(tokens))
        with self._lock:
            cell = self._data.setdefault(
                tenant,
                {o: [0, 0.0, 0] for o in SERVING_OUTCOMES})[outcome]
            cell[0] += 1
            cell[1] += seconds
            cell[2] += tokens
        if self._export:
            _SERVING_SECONDS.inc(seconds, tenant=tenant, outcome=outcome)
            _SERVING_REPLIES.inc(tenant=tenant, outcome=outcome)
            if tokens:
                _SERVING_TOKENS.inc(tokens, tenant=tenant,
                                    outcome=outcome)
        if outcome == "ok":
            (self._accountant or ACCOUNTANT).account("serving", seconds)

    def report(self):
        """-> {goodput, ok/late/shed/error totals, tenants: {name:
        {replies, ok, late, shed, error, seconds, ok_seconds,
        deadline_hit_rate}}}. ``goodput`` is ok-seconds over all
        reply-seconds; ``deadline_hit_rate`` is ok replies over all
        *answered* replies plus sheds (an error or shed is a miss, by
        construction — a request the fleet failed to answer usefully)."""
        with self._lock:
            data = {t: {o: list(c) for o, c in per.items()}
                    for t, per in self._data.items()}
        tenants = {}
        tot = {o: [0, 0.0, 0] for o in SERVING_OUTCOMES}
        for t, per in sorted(data.items()):
            replies = sum(c[0] for c in per.values())
            secs = sum(c[1] for c in per.values())
            toks = sum(c[2] for c in per.values())
            for o in SERVING_OUTCOMES:
                tot[o][0] += per[o][0]
                tot[o][1] += per[o][1]
                tot[o][2] += per[o][2]
            tenants[t] = {
                "replies": replies,
                **{o: per[o][0] for o in SERVING_OUTCOMES},
                "seconds": round(secs, 6),
                "ok_seconds": round(per["ok"][1], 6),
                "tokens": toks,
                "ok_tokens": per["ok"][2],
                "deadline_hit_rate": (round(per["ok"][0] / replies, 6)
                                      if replies else 0.0),
                "token_hit_rate": (round(per["ok"][2] / toks, 6)
                                   if toks else 0.0),
            }
        total_s = sum(c[1] for c in tot.values())
        total_n = sum(c[0] for c in tot.values())
        total_tok = sum(c[2] for c in tot.values())
        return {
            "goodput": (round(tot["ok"][1] / total_s, 6)
                        if total_s > 0 else 0.0),
            # the token-workload goodput: in-SLO tokens over ALL
            # streamed tokens (0.0 while nothing streamed)
            "goodput_tokens": (round(tot["ok"][2] / total_tok, 6)
                               if total_tok > 0 else 0.0),
            "replies": total_n,
            **{o: tot[o][0] for o in SERVING_OUTCOMES},
            "total_seconds": round(total_s, 6),
            "ok_seconds": round(tot["ok"][1], 6),
            "tokens": total_tok,
            "ok_tokens": tot["ok"][2],
            "tenants": tenants,
        }

    def reset(self):
        with self._lock:
            self._data = {}


#: Default process serving-goodput ledger; the fleet router feeds it.
SERVING_LEDGER = ServingGoodput()
