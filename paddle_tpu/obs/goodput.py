"""Goodput accounting: useful step time vs. everything else.

The fleet papers ("ML Productivity Goodput", PAPERS.md) frame the
production training metric not as step throughput but as the fraction
of wall-clock spent making forward progress — checkpoints, retries,
rollbacks, and idle waits are all throughput a preemption-prone fleet
silently loses. ROADMAP item 3 reduces to this ledger.

Categories:

    step        a useful training step (the numerator)
    checkpoint  save/restore I/O (resilience/checkpoint.py feeds this)
    retry       backoff sleeps (resilience/retry.py feeds this)
    rollback    bad-step checkpoint restores (resilience/badstep.py)
    idle        wall-clock not covered by any recorded category

Use either the context managers::

    acct = goodput.ACCOUNTANT
    with acct.step():        loss = train_step(...)
    with acct.checkpoint():  manager.save(state, step)

or feed pre-measured durations with ``account(category, seconds)`` —
the resilience hooks do the latter so instrumentation never changes
control flow. ``report()`` yields the goodput fraction; the same
numbers are exported as ``paddle_goodput_seconds_total{category=...}``
through the default metrics registry.
"""
import contextlib
import threading
import time

from . import metrics as _metrics

CATEGORIES = ("step", "checkpoint", "retry", "rollback", "idle")

_SECONDS = _metrics.counter(
    "paddle_goodput_seconds_total",
    "Wall-clock seconds per goodput category (step = useful time)",
    labelnames=("category",))
_EVENTS = _metrics.counter(
    "paddle_goodput_events_total",
    "Recorded goodput events per category",
    labelnames=("category",))


class GoodputAccountant:
    """Thread-safe per-category time ledger.

    Wall-clock (for the idle residual) runs from the first recorded
    event to the last; a quiet accountant reports goodput 0.0 rather
    than inventing a denominator.
    """

    def __init__(self, export=True):
        self._lock = threading.Lock()
        self._totals = {c: 0.0 for c in CATEGORIES}
        self._counts = {c: 0 for c in CATEGORIES}
        self._t_first = None
        self._t_last = None
        self._export = export

    def account(self, category, seconds):
        if category not in CATEGORIES:
            raise ValueError(
                f"unknown goodput category {category!r} "
                f"(have {CATEGORIES})")
        seconds = max(0.0, float(seconds))
        now = time.monotonic()
        with self._lock:
            self._totals[category] += seconds
            self._counts[category] += 1
            if self._t_first is None:
                self._t_first = now - seconds
            self._t_last = now
        if self._export:
            _SECONDS.inc(seconds, category=category)
            _EVENTS.inc(category=category)

    @contextlib.contextmanager
    def _timed(self, category):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.account(category, time.perf_counter() - t0)

    def step(self):
        return self._timed("step")

    def checkpoint(self):
        return self._timed("checkpoint")

    def retry(self):
        return self._timed("retry")

    def rollback(self):
        return self._timed("rollback")

    def report(self):
        """-> {<cat>_s, steps, total_s, goodput}. ``idle_s`` is the
        first-to-last-event wall-clock not covered by any recorded
        category (plus anything accounted explicitly as idle)."""
        with self._lock:
            totals = dict(self._totals)
            steps = self._counts["step"]
            wall = ((self._t_last - self._t_first)
                    if self._t_first is not None else 0.0)
        accounted = sum(totals.values())
        idle = totals["idle"] + max(0.0, wall - accounted)
        total = max(wall, accounted)
        out = {f"{c}_s": round(totals[c], 6) for c in CATEGORIES}
        out["idle_s"] = round(idle, 6)
        out["steps"] = steps
        out["total_s"] = round(total, 6)
        out["goodput"] = round(totals["step"] / total, 6) if total else 0.0
        # the fleet-facing rate (bench.py goodput): useful steps per
        # wall-clock hour, the "ML Productivity Goodput" numerator
        out["steps_per_hour"] = (round(steps / total * 3600.0, 3)
                                 if total else 0.0)
        return out

    def reset(self):
        with self._lock:
            self._totals = {c: 0.0 for c in CATEGORIES}
            self._counts = {c: 0 for c in CATEGORIES}
            self._t_first = self._t_last = None


#: Default process accountant; the resilience runtime feeds it.
ACCOUNTANT = GoodputAccountant()


def account(category, seconds):
    ACCOUNTANT.account(category, seconds)


def report():
    return ACCOUNTANT.report()
