"""Span tracing: one clock, one summary table, request-scoped trace ids.

A *span* is a named timed region. Spans come from three places and all
land in the same aggregation table and the same bounded buffer of
finished spans:

- serving: per-request spans over enqueue -> batch -> (compile) ->
  execute -> reply, tagged with the request's ``trace_id`` (propagated
  from the client over the wire — see inference/server.py
  TRACE_MARKER);
- training: per-step spans feeding the goodput accountant
  (obs/goodput.py);
- the legacy ``utils.profiler.RecordEvent`` API, which now routes here
  (its ``summary()`` printer reads :func:`summary_rows`), so BENCH
  profiles and serving spans share one clock (``time.perf_counter``)
  and one table.

Trace ids are 64-bit, non-zero, hex-rendered; ``trace(tid)`` installs
an ambient id for the current thread that ``span()``/``start_span()``
inherit, and explicit ``trace_id=`` wins — the engine scheduler runs in
a different thread from the submitting handler, so the id travels on
the request object, not on the thread.
"""
import collections
import contextlib
import os
import random
import threading
import time

_BUFFER_CAP = int(os.environ.get("PADDLE_TPU_OBS_SPAN_BUFFER", "4096"))

_lock = threading.Lock()
_finished = collections.deque(maxlen=_BUFFER_CAP)
_agg = {}  # name -> [calls, total_s, max_s, min_s]
_tls = threading.local()
_span_seq = [0]


def new_trace_id():
    """Random non-zero u64 (0 means "no trace" on the wire)."""
    tid = 0
    while tid == 0:
        tid = random.getrandbits(64)
    return tid


def format_trace_id(tid):
    return f"{tid:016x}"


def current_trace_id():
    """The ambient trace id installed by :func:`trace` (None outside)."""
    return getattr(_tls, "trace_id", None)


@contextlib.contextmanager
def trace(trace_id):
    """Install ``trace_id`` as the current thread's ambient id."""
    prev = getattr(_tls, "trace_id", None)
    _tls.trace_id = trace_id
    try:
        yield trace_id
    finally:
        _tls.trace_id = prev


class Span:
    """One timed region. Created by :func:`start_span`; must be
    :meth:`finish`-ed (or used via the :func:`span` context manager).
    A Span may be finished from a different thread than it was started
    on — the engine scheduler finishes queue spans the handler thread
    opened."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t_start", "duration_s", "_done")

    def __init__(self, name, trace_id=None, parent_id=None, attrs=None):
        self.name = name
        self.trace_id = (trace_id if trace_id is not None
                         else current_trace_id())
        with _lock:
            _span_seq[0] += 1
            self.span_id = _span_seq[0]
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.t_start = time.perf_counter()
        self.duration_s = None
        self._done = False

    def finish(self, **attrs):
        """Record the span (idempotent). Extra attrs merge in."""
        if self._done:
            return self
        self._done = True
        self.duration_s = time.perf_counter() - self.t_start
        if attrs:
            self.attrs.update(attrs)
        _record(self)
        return self

    def as_dict(self):
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "duration_s": self.duration_s, "attrs": dict(self.attrs)}


def start_span(name, trace_id=None, parent_id=None, **attrs):
    return Span(name, trace_id=trace_id, parent_id=parent_id, attrs=attrs)


@contextlib.contextmanager
def span(name, trace_id=None, **attrs):
    sp = start_span(name, trace_id=trace_id, **attrs)
    try:
        yield sp
    finally:
        sp.finish()


def _agg_update_locked(name, duration_s):
    """Fold one duration into the summary table. Caller holds _lock."""
    rec = _agg.get(name)
    if rec is None:
        rec = _agg[name] = [0, 0.0, 0.0, float("inf")]
    rec[0] += 1
    rec[1] += duration_s
    rec[2] = max(rec[2], duration_s)
    rec[3] = min(rec[3], duration_s)


def _record(sp):
    with _lock:
        _finished.append(sp.as_dict())
        _agg_update_locked(sp.name, sp.duration_s)


def observe(name, duration_s):
    """Aggregate a pre-measured duration into the summary table only —
    no buffer entry, no Span object (the cheap path for untraced hot
    traffic)."""
    with _lock:
        _agg_update_locked(name, float(duration_s))


def record_span(name, duration_s, trace_id=None, parent_id=None, **attrs):
    """Record an already-measured region as a finished span (the
    engine measures one batch execute and attributes it to every traced
    request in the group)."""
    sp = Span.__new__(Span)
    sp.name = name
    sp.trace_id = trace_id if trace_id is not None else current_trace_id()
    with _lock:
        _span_seq[0] += 1
        sp.span_id = _span_seq[0]
    sp.parent_id = parent_id
    sp.attrs = dict(attrs)
    sp.t_start = time.perf_counter() - duration_s
    sp.duration_s = float(duration_s)
    sp._done = True
    _record(sp)
    return sp


def finished(trace_id=None, name=None):
    """Finished spans (as dicts, oldest first), optionally filtered by
    trace id and/or span name. The buffer is bounded
    (PADDLE_TPU_OBS_SPAN_BUFFER, default 4096): this is a debugging /
    test surface, not a durable trace store."""
    with _lock:
        spans = list(_finished)
    if trace_id is not None:
        spans = [s for s in spans if s["trace_id"] == trace_id]
    if name is not None:
        spans = [s for s in spans if s["name"] == name]
    return spans


def summary_rows():
    """Aggregated per-name rows, the profiler.summary() table schema:
    {name, calls, total, avg, max, min}."""
    with _lock:
        return [{"name": n, "calls": c, "total": tot, "avg": tot / c,
                 "max": mx, "min": mn}
                for n, (c, tot, mx, mn) in _agg.items()]


def reset_summary():
    """Clear the aggregation table (the profiler.reset_summary()
    contract); the finished-span buffer survives."""
    with _lock:
        _agg.clear()


def reset():
    """Clear both the aggregation table and the finished-span buffer."""
    with _lock:
        _agg.clear()
        _finished.clear()
