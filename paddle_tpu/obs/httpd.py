"""Minimal /metrics HTTP endpoint (Prometheus scrape target).

``serve_model(..., metrics_port=N)`` starts one of these next to the
serving port; operators who prefer the wire protocol can use the
``metrics`` command (cmd 6) on the serving socket instead — both render
the same registry.
"""
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import prometheus


class MetricsServer:
    """Threaded HTTP server answering GET /metrics with the text
    exposition of ``registry`` (default: the process registry)."""

    def __init__(self, port=0, host="127.0.0.1", registry=None):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = prometheus.render(srv._registry).encode("utf-8")
                except Exception as e:  # noqa: BLE001 — scrape must not 500 silently
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", prometheus.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not access-log news
                pass

        self._registry = registry
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="obs-metrics-http",
                                        daemon=True)
        self._thread.start()

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
