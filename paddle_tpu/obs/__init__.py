"""Unified telemetry: metrics registry, span tracing, goodput
accounting, compile ledger, Prometheus exposition.

One instrumentation layer for training, serving, and CI (ROADMAP items
3 and 4). Pure-stdlib on purpose: importable from the resilience
runtime, the serving engine, clients of the wire protocol, and
bench.py without dragging jax into anything that doesn't already have
it.

Quick tour::

    from paddle_tpu import obs

    reqs = obs.counter("myapp_requests_total", "requests served")
    reqs.inc()
    lat = obs.histogram("myapp_latency_seconds", "request latency")
    lat.observe(0.012)
    print(obs.render())               # Prometheus text exposition

    with obs.tracing.span("myapp.handler", trace_id=obs.new_trace_id()):
        ...                           # lands in the shared span table

    obs.goodput.account("checkpoint", 2.5)
    obs.goodput.report()              # {"goodput": ..., "step_s": ...}

    obs.LEDGER.record("mykernel", duration_s=dt, compiled=compiled)
"""
from . import goodput, ledger, metrics, prometheus, tracing  # noqa: F401
from .ledger import LEDGER, CompileLedger  # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge, Histogram,  # noqa: F401
                      Registry, counter, gauge, histogram, log_buckets)
from .prometheus import render  # noqa: F401
from .tracing import new_trace_id, span, start_span  # noqa: F401

__all__ = [
    "metrics", "prometheus", "tracing", "goodput", "ledger",
    "REGISTRY", "Registry", "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram", "log_buckets", "render",
    "LEDGER", "CompileLedger", "new_trace_id", "span", "start_span",
]
