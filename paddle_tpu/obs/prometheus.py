"""Prometheus text exposition (format version 0.0.4) over a
:class:`~paddle_tpu.obs.metrics.Registry`.

Families with the same name (e.g. two batching engines each exposing
``paddle_serving_requests_total`` through their collectors) are merged
under one HELP/TYPE header; duplicate (name, labels) sample keys are
summed — the semantics an aggregating scraper would apply anyway, and
the only correct merge for counters/histogram buckets.
"""
from .metrics import REGISTRY, _format_float

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(s):
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _render_labels(labels):
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render(registry=None):
    """-> the exposition text for every family the registry collects."""
    registry = registry if registry is not None else REGISTRY
    merged = {}  # name -> (kind, help, {(suffix, label_items): value})
    order = []
    for fam in registry.collect():
        if fam.name not in merged:
            merged[fam.name] = (fam.kind, fam.help, {})
            order.append(fam.name)
        kind, help_, samples = merged[fam.name]
        if kind != fam.kind:
            raise ValueError(
                f"family {fam.name!r} collected with conflicting kinds "
                f"{kind!r} and {fam.kind!r}")
        for suffix, labels, value in fam.samples:
            key = (suffix, tuple(sorted((str(k), str(v))
                                        for k, v in labels.items())))
            samples[key] = samples.get(key, 0.0) + value
    lines = []
    for name in sorted(order):
        kind, help_, samples = merged[name]
        if help_:
            lines.append(f"# HELP {name} {_escape_help(help_)}")
        lines.append(f"# TYPE {name} {kind or 'untyped'}")
        for (suffix, label_items), value in samples.items():
            lines.append(f"{name}{suffix}"
                         f"{_render_labels(dict(label_items))} "
                         f"{_format_float(value)}")
    return "\n".join(lines) + "\n" if lines else ""
