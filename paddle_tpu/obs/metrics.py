"""Process-wide metrics registry: counters, gauges, histograms.

One instrumentation layer that training, serving, and CI all report
through (ROADMAP items 3 and 4 both reduce to this): the serving
engine's per-bucket counters, the server's connection/frame counters,
and the resilience runtime's checkpoint/retry/rollback counters all
register here, and the cmd-5 ``stats`` / cmd-3 ``health`` wire commands
plus the Prometheus exposition (``obs.prometheus.render``) are views
over the same instruments — no more ad-hoc dicts that each surface
re-invents.

Design points:

- **Lock-cheap**: each instrument carries one small lock around a dict
  update; hot paths (the engine scheduler) already hold the engine lock
  at increment sites, so there is never lock contention beyond the GIL.
- **Snapshot-consistent**: ``Registry.collect()`` copies registered
  instruments under the registry lock, then runs collectors OUTSIDE it
  — a collector (e.g. the batching engine's) takes its own subsystem
  lock and emits every sample from one consistent view. The lock order
  is always subsystem-lock -> instrument-lock, never the reverse, so
  exposition can never deadlock against the hot path.
- **Instruments work standalone**: a subsystem may build private
  Counter/Gauge/Histogram objects (per-engine, per-server) and expose
  them through a registered collector instead of claiming global metric
  names — two engines then contribute samples to the same family,
  distinguished by their const labels.
- **Histograms use fixed log-spaced buckets** (:func:`log_buckets`):
  latency distributions span decades, and fixed buckets keep observe()
  O(#buckets) with zero allocation.
"""
import bisect
import math
import re
import threading

# Machine-checked lock order (tools/tracelint.py --concurrency, TPU309):
# registration may hold the registry lock while touching instruments,
# but instrument code must NEVER call back into the registry while
# holding its own lock — the reverse edge is the exposition-deadlock
# this module's docstring argues can't happen. Now it is checked.
# tpu-lock-order: Registry._lock < Metric._lock  # instruments never re-enter the registry

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_RESERVED_LABELS = frozenset({"le", "quantile"})


def log_buckets(start=0.0001, factor=4.0, count=12):
    """Fixed log-spaced histogram bucket upper bounds:
    ``start * factor**i`` for i in [0, count). The default spans 100us
    to ~420s at 4x resolution — wide enough for queue waits, batch
    execs, XLA compiles, and checkpoint writes with one shape."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


DEFAULT_BUCKETS = log_buckets()


def _check_labels(labelnames):
    for ln in labelnames:
        if not _LABEL_NAME_RE.match(ln) or ln in _RESERVED_LABELS:
            raise ValueError(f"invalid label name {ln!r}")
    return tuple(labelnames)


class Family:
    """One exposition family: every sample a metric contributes under
    one name. ``samples`` rows are (suffix, labels_dict, value)."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name, kind, help, samples):
        self.name = name
        self.kind = kind
        self.help = help
        self.samples = samples


class Metric:
    """Base instrument: a named family of samples keyed by label
    values. Usable standalone or registered in a :class:`Registry`."""

    kind = None

    def __init__(self, name, help="", labelnames=(), const_labels=None):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = _check_labels(labelnames)
        self.const_labels = dict(const_labels or {})
        _check_labels(self.const_labels)
        self._lock = threading.Lock()
        self._values = {}  # label-value tuple -> store

    def _key(self, labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _label_dict(self, key):
        d = dict(self.const_labels)
        d.update(zip(self.labelnames, key))
        return d

    def _new_store(self):
        return 0.0

    def _store(self, key):
        """Called with self._lock held."""
        st = self._values.get(key)
        if st is None:
            st = self._values[key] = self._new_store()
        return st

    def clear(self, **labels):
        """Drop one label child (or every sample with no labels given)
        — long-lived registries shed per-test engines this way."""
        with self._lock:
            if labels:
                self._values.pop(self._key(labels), None)
            else:
                self._values.clear()


class Counter(Metric):
    """Monotonic counter. By convention the name ends in ``_total``."""

    kind = "counter"

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._store(key) + amount

    def value(self, **labels):
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def collect(self):
        with self._lock:
            samples = [("", self._label_dict(k), v)
                       for k, v in sorted(self._values.items())]
        if not self.labelnames and not samples:
            samples = [("", dict(self.const_labels), 0.0)]
        return Family(self.name, self.kind, self.help, samples)


class Gauge(Metric):
    """Point-in-time value (queue depth, heartbeat age, goodput)."""

    kind = "gauge"

    def set(self, value, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount=1, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._store(key) + amount

    def dec(self, amount=1, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def collect(self):
        with self._lock:
            samples = [("", self._label_dict(k), v)
                       for k, v in sorted(self._values.items())]
        if not self.labelnames and not samples:
            samples = [("", dict(self.const_labels), 0.0)]
        return Family(self.name, self.kind, self.help, samples)


class _HistStore:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(Metric):
    """Distribution with fixed (log-spaced by default) buckets.

    Exposes the Prometheus histogram triplet: cumulative
    ``<name>_bucket{le=...}`` series (always ending in ``le="+Inf"``),
    ``<name>_sum`` and ``<name>_count``.
    """

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), const_labels=None,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, const_labels)
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(set(bs)):
            raise ValueError(f"{name}: buckets must be sorted and unique")
        if math.isinf(bs[-1]):
            bs = bs[:-1]  # +Inf is implicit
        self.buckets = bs

    def _new_store(self):
        return _HistStore(len(self.buckets) + 1)

    def observe(self, value, **labels):
        value = float(value)
        key = self._key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            st = self._store(key)
            st.counts[idx] += 1
            st.sum += value
            st.count += 1

    def value(self, **labels):
        """-> {"count": n, "sum": s} for one label child."""
        with self._lock:
            st = self._values.get(self._key(labels))
            if st is None:
                return {"count": 0, "sum": 0.0}
            return {"count": st.count, "sum": st.sum}

    def collect(self):
        samples = []
        with self._lock:
            items = [(k, list(st.counts), st.sum, st.count)
                     for k, st in sorted(self._values.items())]
        for key, counts, total, count in items:
            base = self._label_dict(key)
            acc = 0
            for ub, c in zip(self.buckets, counts):
                acc += c
                le = dict(base)
                le["le"] = _format_float(ub)
                samples.append(("_bucket", le, acc))
            inf = dict(base)
            inf["le"] = "+Inf"
            samples.append(("_bucket", inf, count))
            samples.append(("_sum", base, total))
            samples.append(("_count", base, count))
        return Family(self.name, self.kind, self.help, samples)


def _format_float(v):
    """Shortest exact-ish rendering ("0.001", "2", "+Inf")."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Registry:
    """Named instruments plus collector callbacks.

    ``counter``/``gauge``/``histogram`` are get-or-create: a module that
    is imported twice (or a test that re-runs setup) gets the existing
    instrument back instead of a duplicate-name error — but asking for
    an existing name with a different kind or label schema raises.

    Collectors are zero-arg callables returning an iterable of
    :class:`Family`; they run OUTSIDE the registry lock (see module
    docstring for the lock-order argument) at every ``collect()``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._collectors = []

    # -------------------------------------------------------- registration
    def register(self, metric):
        with self._lock:
            have = self._metrics.get(metric.name)
            if have is not None and have is not metric:
                raise ValueError(
                    f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def _get_or_create(self, cls, name, help, labelnames, const_labels,
                       **kw):
        with self._lock:
            have = self._metrics.get(name)
            if have is not None:
                if (type(have) is not cls
                        or have.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} exists with a different "
                        f"kind/label schema")
                return have
            m = cls(name, help, labelnames, const_labels, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=(), const_labels=None):
        return self._get_or_create(Counter, name, help, labelnames,
                                   const_labels)

    def gauge(self, name, help="", labelnames=(), const_labels=None):
        return self._get_or_create(Gauge, name, help, labelnames,
                                   const_labels)

    def histogram(self, name, help="", labelnames=(), const_labels=None,
                  buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   const_labels, buckets=buckets)

    def register_collector(self, fn):
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)
        return fn

    def unregister_collector(self, fn):
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    # ----------------------------------------------------------- snapshot
    def collect(self):
        """-> list[Family]: registered instruments first, then collector
        families. Collectors run outside the registry lock. A collector
        returning None (vs an empty list) declares itself dead — e.g. a
        weakref-wrapped engine that was garbage-collected without
        close() — and is auto-unregistered."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        families = [m.collect() for m in metrics]
        for fn in collectors:
            fams = fn()
            if fams is None:
                self.unregister_collector(fn)
                continue
            families.extend(fams)
        return families

    def snapshot(self):
        """JSON-able view: {name: [{"labels": {...}, "value": v}, ...]}
        (histogram families expose their _sum/_count/_bucket rows)."""
        out = {}
        for fam in self.collect():
            rows = out.setdefault(fam.name, [])
            for suffix, labels, value in fam.samples:
                rows.append({"sample": fam.name + suffix,
                             "labels": dict(labels), "value": value})
        return out


#: Default process-wide registry — what the Prometheus surfaces
#: (wire cmd 6, serve_model(metrics_port=)) expose.
REGISTRY = Registry()


def counter(name, help="", labelnames=()):
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)
