"""Vision datasets (reference: python/paddle/vision/datasets/: mnist.py,
cifar.py, flowers.py, voc2012.py).

This environment has zero network egress, so datasets load from local
files when present (same formats as the reference's download cache) and
otherwise fall back to a *deterministic synthetic* sample generator with
class-conditional structure — models genuinely learn on it, which keeps
convergence tests meaningful without downloads.
"""
import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

_DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                               "~/.cache/paddle_tpu/dataset"))


def _synthetic_images(n, num_classes, hw, channels, seed, template_seed=1234):
    """Class-conditional Gaussian-blob images: class k has a fixed random
    template (shared between train/test splits — template_seed), samples are
    template + per-split noise (seed). Linearly separable enough for smoke
    training, hard enough that accuracy tracks learning."""
    h, w = hw
    t_rng = np.random.RandomState(template_seed + num_classes * h)
    templates = t_rng.uniform(0.0, 1.0, size=(num_classes, channels, h, w)).astype(
        np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int64)
    noise = rng.normal(0, 0.35, size=(n, channels, h, w)).astype(np.float32)
    images = np.clip(templates[labels] + noise, 0.0, 1.0)
    return images, labels


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py (IDX file format)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        base = os.path.join(_DATA_HOME, "mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._parse_idx(image_path, label_path)
        else:
            n = 8192 if mode == "train" else 1024
            imgs, labels = _synthetic_images(n, 10, (28, 28), 1, seed=42
                                             if mode == "train" else 43)
            self.images = (imgs[:, 0] * 255).astype(np.uint8)
            self.labels = labels

    @staticmethod
    def _parse_idx(image_path, label_path):
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # [1, 28, 28]
        img = img / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


FashionMNIST = MNIST


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py."""

    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend="cv2"):
        self.mode = mode
        self.transform = transform
        n = 8192 if mode == "train" else 1024
        imgs, labels = _synthetic_images(n, self.NUM_CLASSES, (32, 32), 3,
                                         seed=44 if mode == "train" else 45)
        self.images = imgs
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend="cv2"):
        n = 2048 if mode == "train" else 512
        imgs, labels = _synthetic_images(n, 102, (64, 64), 3, seed=46)
        self.images = imgs
        self.labels = labels
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)
