"""Vision datasets (reference: python/paddle/vision/datasets/: mnist.py,
cifar.py, flowers.py, voc2012.py).

This environment has zero network egress, so datasets load from local
files when present (same formats as the reference's download cache) and
otherwise fall back to a *deterministic synthetic* sample generator with
class-conditional structure — models genuinely learn on it, which keeps
convergence tests meaningful without downloads.
"""
import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

_DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                               "~/.cache/paddle_tpu/dataset"))


def _synthetic_images(n, num_classes, hw, channels, seed, template_seed=1234):
    """Class-conditional Gaussian-blob images: class k has a fixed random
    template (shared between train/test splits — template_seed), samples are
    template + per-split noise (seed). Linearly separable enough for smoke
    training, hard enough that accuracy tracks learning."""
    h, w = hw
    t_rng = np.random.RandomState(template_seed + num_classes * h)
    templates = t_rng.uniform(0.0, 1.0, size=(num_classes, channels, h, w)).astype(
        np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int64)
    noise = rng.normal(0, 0.35, size=(n, channels, h, w)).astype(np.float32)
    images = np.clip(templates[labels] + noise, 0.0, 1.0)
    return images, labels


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py (IDX file format)."""

    _DIR = "mnist"
    _SEEDS = (42, 43)          # (train, test) sample noise seeds
    _TEMPLATE_SEED = 1234      # class templates (shared across splits)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        base = os.path.join(_DATA_HOME, self._DIR)
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._parse_idx(image_path, label_path)
        else:
            n = 8192 if mode == "train" else 1024
            imgs, labels = _synthetic_images(
                n, 10, (28, 28), 1,
                seed=self._SEEDS[0] if mode == "train" else self._SEEDS[1],
                template_seed=self._TEMPLATE_SEED)
            self.images = (imgs[:, 0] * 255).astype(np.uint8)
            self.labels = labels

    @staticmethod
    def _parse_idx(image_path, label_path):
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # [1, 28, 28]
        img = img / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    """reference: vision/datasets/mnist.py:180 — same IDX format, its own
    files/cache dir; the synthetic fallback uses distinct class templates
    so MNIST- and FashionMNIST-trained models are not interchangeable."""

    _DIR = "fashion-mnist"
    _SEEDS = (52, 53)
    _TEMPLATE_SEED = 5678


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py."""

    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend="cv2"):
        self.mode = mode
        self.transform = transform
        n = 8192 if mode == "train" else 1024
        imgs, labels = _synthetic_images(n, self.NUM_CLASSES, (32, 32), 3,
                                         seed=44 if mode == "train" else 45)
        self.images = imgs
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class VOC2012(Dataset):
    """Semantic segmentation pairs (image [3,H,W] float, mask [H,W] int64
    in 0..20) (reference: vision/datasets/voc2012.py). Synthetic
    fallback: class-colored rectangles on background 0 — the mask is
    exactly recoverable from the image, so segmentation models can fit."""

    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        if mode not in ("train", "valid", "test"):
            raise ValueError(f"mode must be train/valid/test, got {mode!r}")
        self.transform = transform
        n = 512 if mode == "train" else 128
        hw = 64
        rng = np.random.RandomState({"train": 60, "valid": 61,
                                     "test": 62}[mode])
        trng = np.random.RandomState(4321)
        palette = trng.uniform(0.2, 1.0, size=(self.NUM_CLASSES, 3)) \
            .astype(np.float32)
        palette[0] = 0.05  # background
        self.images = np.zeros((n, 3, hw, hw), np.float32)
        self.masks = np.zeros((n, hw, hw), np.int64)
        for i in range(n):
            img = np.broadcast_to(palette[0].reshape(3, 1, 1),
                                  (3, hw, hw)).copy()
            mask = np.zeros((hw, hw), np.int64)
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, self.NUM_CLASSES))
                y0, x0 = rng.randint(0, hw - 8, size=2)
                dy, dx = rng.randint(8, 24, size=2)
                img[:, y0:y0 + dy, x0:x0 + dx] = palette[cls].reshape(3, 1, 1)
                mask[y0:y0 + dy, x0:x0 + dx] = cls
            noise = rng.normal(0, 0.02, size=img.shape).astype(np.float32)
            self.images[i] = np.clip(img + noise, 0, 1)
            self.masks[i] = mask

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image

    with open(path, "rb") as f:
        img = Image.open(f)
        return np.asarray(img.convert("RGB"))


class DatasetFolder(Dataset):
    """``root/class_x/xxx.ext`` directory-tree dataset (reference:
    vision/datasets/folder.py:65). Fully real — no synthetic fallback;
    images load via PIL as HWC uint8 arrays."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for dirpath, _, fnames in sorted(os.walk(d)):
                for fname in sorted(fnames):
                    p = os.path.join(dirpath, fname)
                    if is_valid_file(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (unlabeled) image-folder dataset yielding ``[img]`` rows
    (reference: vision/datasets/folder.py:222)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        self.samples = []
        for dirpath, _, fnames in sorted(os.walk(root)):
            for fname in sorted(fnames):
                p = os.path.join(dirpath, fname)
                if is_valid_file(p):
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend="cv2"):
        n = 2048 if mode == "train" else 512
        imgs, labels = _synthetic_images(n, 102, (64, 64), 3, seed=46)
        self.images = imgs
        self.labels = labels
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


from ..core.module_alias import alias_submodules as _alias

_alias(__name__, "mnist", "cifar", "flowers", "folder", "voc2012")
