"""Vision ops (reference: operators/detection/: yolo_box, roi_align, nms...).
Round-1 subset: roi_align, nms, yolo helpers later."""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply_op, in_trace
from ..core.tensor import Tensor
from ..core import errors


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (data-dependent output shape — eager only)."""
    if in_trace():
        raise errors.UnimplementedError("nms is not traceable (dynamic shape)")
    b = np.asarray(boxes._value)
    s = np.asarray(scores._value) if scores is not None else np.ones(len(b))
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_o = (b[order[1:], 2] - b[order[1:], 0]) * \
                 (b[order[1:], 3] - b[order[1:], 1])
        iou = inter / (area_i + area_o - inter + 1e-12)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Bilinear ROI-Align (reference: operators/detection/roi_align_op.cc)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def _roi_align(x, boxes, *, out_hw, scale, aligned):
        oh, ow = out_hw
        n, c, h, w = x.shape

        def one_roi(box):
            off = 0.5 if aligned else 0.0
            x1 = box[0] * scale - off
            y1 = box[1] * scale - off
            x2 = box[2] * scale - off
            y2 = box[3] * scale - off
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            ys = y1 + (jnp.arange(oh) + 0.5) * rh / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * rw / ow
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xs - x0, 0, 1)
            img = x[0]
            va = img[:, y0][:, :, x0]
            vb = img[:, y0][:, :, x1i]
            vc = img[:, y1i][:, :, x0]
            vd = img[:, y1i][:, :, x1i]
            top = va * (1 - wx)[None, None, :] + vb * wx[None, None, :]
            bot = vc * (1 - wx)[None, None, :] + vd * wx[None, None, :]
            return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

        import jax

        return jax.vmap(one_roi)(boxes)

    return apply_op("roi_align", _roi_align, x, boxes, out_hw=tuple(output_size),
                    scale=float(spatial_scale), aligned=bool(aligned))
