"""Vision ops (reference: operators/detection/: yolo_box, roi_align, nms...).
Round-1 subset: roi_align, nms, yolo helpers later."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op, in_trace
from ..core.tensor import Tensor
from ..core import errors


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (data-dependent output shape — eager only)."""
    if in_trace():
        raise errors.UnimplementedError("nms is not traceable (dynamic shape)")
    b = np.asarray(boxes._value)
    s = np.asarray(scores._value) if scores is not None else np.ones(len(b))
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        area_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        area_o = (b[order[1:], 2] - b[order[1:], 0]) * \
                 (b[order[1:], 3] - b[order[1:], 1])
        iou = inter / (area_i + area_o - inter + 1e-12)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Bilinear ROI-Align (reference: operators/detection/roi_align_op.cc)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def _roi_align(x, boxes, *, out_hw, scale, aligned):
        oh, ow = out_hw
        n, c, h, w = x.shape

        def one_roi(box):
            off = 0.5 if aligned else 0.0
            x1 = box[0] * scale - off
            y1 = box[1] * scale - off
            x2 = box[2] * scale - off
            y2 = box[3] * scale - off
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            ys = y1 + (jnp.arange(oh) + 0.5) * rh / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * rw / ow
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xs - x0, 0, 1)
            img = x[0]
            va = img[:, y0][:, :, x0]
            vb = img[:, y0][:, :, x1i]
            vc = img[:, y1i][:, :, x0]
            vd = img[:, y1i][:, :, x1i]
            top = va * (1 - wx)[None, None, :] + vb * wx[None, None, :]
            bot = vc * (1 - wx)[None, None, :] + vd * wx[None, None, :]
            return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

        import jax

        return jax.vmap(one_roi)(boxes)

    return apply_op("roi_align", _roi_align, x, boxes, out_hw=tuple(output_size),
                    scale=float(spatial_scale), aligned=bool(aligned))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (reference:
    operators/detection/yolo_box_op.cc). Pure jnp (traceable): returns
    (boxes [N, H*W*A, 4] in xyxy image coords, scores [N, H*W*A, C]);
    the conf_thresh zeroes low-confidence scores instead of filtering
    (static shapes for XLA)."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)

    def _yolo(x, img_size, *, an, cnum, conf, ds, clip, sxy):
        an = jnp.asarray(an, jnp.float32)  # hashable tuple -> array
        n, c, h, w = x.shape
        a = an.shape[0]
        x = x.reshape(n, a, cnum + 5, h, w)
        gx = (jnp.arange(w, dtype=jnp.float32))[None, None, None, :]
        gy = (jnp.arange(h, dtype=jnp.float32))[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (sig(x[:, :, 0]) * sxy - 0.5 * (sxy - 1) + gx) / w
        by = (sig(x[:, :, 1]) * sxy - 0.5 * (sxy - 1) + gy) / h
        bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (w * ds)
        bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (h * ds)
        obj = sig(x[:, :, 4])
        cls = sig(x[:, :, 5:])
        imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = (obj[..., None] * jnp.moveaxis(cls, 2, -1))
        scores = jnp.where(obj[..., None] > conf, scores, 0.0)
        return boxes, scores.reshape(n, -1, cnum)

    return apply_op("yolo_box", _yolo, x, img_size,
                    an=tuple(map(tuple, anchors.tolist())),
                    cnum=int(class_num), conf=float(conf_thresh),
                    ds=float(downsample_ratio), clip=bool(clip_bbox),
                    sxy=float(scale_x_y))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes (reference:
    operators/detection/prior_box_op.cc). Returns (boxes [H, W, A, 4]
    normalized xyxy, variances same shape)."""
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - e) > 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    min_sizes = [float(m) for m in np.atleast_1d(min_sizes)]
    max_sizes = [float(m) for m in np.atleast_1d(max_sizes)] \
        if max_sizes is not None else []

    def _prior(feat, img, *, ars, mins, maxs, var, steps, offset, clip):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sw = steps[0] or iw / fw
        sh = steps[1] or ih / fh
        cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * sw
        cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * sh
        whs = []
        for k, ms in enumerate(mins):
            whs.append((ms, ms))
            if k < len(maxs):
                s = float(np.sqrt(ms * maxs[k]))
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        wh = jnp.asarray(whs, jnp.float32)  # [A, 2]
        cxg, cyg = jnp.meshgrid(cx, cy)     # [fh, fw]
        x1 = (cxg[..., None] - wh[None, None, :, 0] / 2) / iw
        y1 = (cyg[..., None] - wh[None, None, :, 1] / 2) / ih
        x2 = (cxg[..., None] + wh[None, None, :, 0] / 2) / iw
        y2 = (cyg[..., None] + wh[None, None, :, 1] / 2) / ih
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32),
                                     boxes.shape)
        return boxes, variances

    return apply_op("prior_box", _prior, input, image, ars=tuple(ars),
                    mins=tuple(min_sizes), maxs=tuple(max_sizes),
                    var=tuple(float(v) for v in variance),
                    steps=tuple(float(s) for s in steps),
                    offset=float(offset), clip=bool(clip))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference:
    operators/detection/box_coder_op.cc)."""
    ct = code_type.lower()
    if ct not in ("encode_center_size", "decode_center_size"):
        raise ValueError(code_type)

    def _coder(prior, pvar, target, *, decode, norm):
        off = 0.0 if norm else 1.0
        pw = prior[:, 2] - prior[:, 0] + off
        ph = prior[:, 3] - prior[:, 1] + off
        pcx = prior[:, 0] + pw / 2
        pcy = prior[:, 1] + ph / 2
        if pvar is None:
            pvar = jnp.ones_like(prior)
        if not decode:
            # output [N_target, N_prior, 4] (reference box_coder_op.cc
            # EncodeCenterSize layout)
            tw = target[:, 2] - target[:, 0] + off
            th = target[:, 3] - target[:, 1] + off
            tcx = target[:, 0] + tw / 2
            tcy = target[:, 1] + th / 2
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :]),
            ], axis=-1) / pvar[None, :, :]
            return out
        # decode: target [N, A, 4] deltas -> boxes
        d = target * pvar[None, :, :] if target.ndim == 3 else \
            (target * pvar)[None]
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=-1)

    return apply_op("box_coder", _coder, prior_box, prior_box_var,
                    target_box, decode=(ct == "decode_center_size"),
                    norm=bool(box_normalized))


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   background_label=-1, name=None):
    """Per-class NMS over [N_box, 4] boxes + [C, N_box] scores (reference:
    operators/detection/multiclass_nms_op.cc, single-image form).
    Host-side (dynamic output shape — eager only). Returns
    [M, 6] rows of (class, score, x1, y1, x2, y2)."""
    if in_trace():
        raise errors.UnimplementedError(
            "multiclass_nms is not traceable (dynamic shape)")
    b = np.asarray(bboxes._value if isinstance(bboxes, Tensor) else bboxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores)
    out = []
    for c in range(s.shape[0]):
        if c == background_label:
            continue
        sel = np.where(s[c] > score_threshold)[0]
        if sel.size == 0:
            continue
        order = sel[np.argsort(-s[c][sel])]
        if nms_top_k > 0:
            order = order[:nms_top_k]
        keep = np.asarray(nms(Tensor(b[order]),
                              iou_threshold=nms_threshold,
                              scores=Tensor(s[c][order]))._value)
        for i in keep:
            gi = order[i]
            out.append([c, s[c][gi], *b[gi]])
    out.sort(key=lambda r: -r[1])
    if keep_top_k > 0:
        out = out[:keep_top_k]
    return Tensor(np.asarray(out, np.float32).reshape(-1, 6))


def anchor_generator(input, anchor_sizes, aspect_ratios=(1.0,),
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5):
    """Dense anchors per feature-map cell (reference:
    operators/detection/anchor_generator_op.h GenAnchors): for each
    (h, w) cell, anchors of every (size, ratio) centered at
    (w*stride_w + offset*(stride_w-1), ...). Returns (anchors [H,W,A,4]
    xyxy, variances [H,W,A,4])."""

    def _gen(x, *, sizes, ratios, variances, stride, offset):
        H, W = x.shape[2], x.shape[3]
        sw, sh = stride
        xc = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)
        yc = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)
        combos = []
        for r in ratios:
            for s in sizes:
                # reference: area = stride_w*stride_h scaled; anchor w/h
                # derived from size and sqrt(ratio)
                ar = jnp.sqrt(jnp.asarray(r, jnp.float32))
                w_a = s / ar
                h_a = s * ar
                combos.append((w_a, h_a))
        A = len(combos)
        ws = jnp.asarray([c[0] for c in combos], jnp.float32)
        hs = jnp.asarray([c[1] for c in combos], jnp.float32)
        xg = xc[None, :, None]
        yg = yc[:, None, None]
        out = jnp.stack([
            jnp.broadcast_to(xg - 0.5 * ws, (H, W, A)),
            jnp.broadcast_to(yg - 0.5 * hs, (H, W, A)),
            jnp.broadcast_to(xg + 0.5 * ws, (H, W, A)),
            jnp.broadcast_to(yg + 0.5 * hs, (H, W, A)),
        ], axis=-1)
        var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                               (H, W, A, 4))
        return out, var

    return apply_op("anchor_generator", _gen, input,
                    sizes=tuple(float(s) for s in anchor_sizes),
                    ratios=tuple(float(r) for r in aspect_ratios),
                    variances=tuple(float(v) for v in variances),
                    stride=tuple(float(s) for s in stride),
                    offset=float(offset))


def iou_similarity(x, y, box_normalized=True):
    """Pairwise IoU matrix [N,M] (reference:
    operators/detection/iou_similarity_op.h)."""

    def _iou(x, y, *, norm):
        off = 0.0 if norm else 1.0
        ax1, ay1, ax2, ay2 = x[:, 0], x[:, 1], x[:, 2], x[:, 3]
        bx1, by1, bx2, by2 = y[:, 0], y[:, 1], y[:, 2], y[:, 3]
        area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
        area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
        ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
        iy1 = jnp.maximum(ay1[:, None], by1[None, :])
        ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
        iy2 = jnp.minimum(ay2[:, None], by2[None, :])
        inter = jnp.clip(ix2 - ix1 + off, 0) * jnp.clip(iy2 - iy1 + off, 0)
        return inter / jnp.maximum(
            area_a[:, None] + area_b[None, :] - inter, 1e-10)

    return apply_op("iou_similarity", _iou, x, y, norm=bool(box_normalized))


def box_clip(input, im_info):
    """Clip xyxy boxes to image bounds (reference:
    operators/detection/box_clip_op.h): im_info rows are
    [height, width, scale]."""

    def _clip(boxes, info):
        # reference box_clip_op.h rounds h/w/scale before the -1
        h = jnp.round(info[..., 0:1] / info[..., 2:3]) - 1.0
        w = jnp.round(info[..., 1:2] / info[..., 2:3]) - 1.0
        x1 = jnp.clip(boxes[..., 0::4], 0.0, w)
        y1 = jnp.clip(boxes[..., 1::4], 0.0, h)
        x2 = jnp.clip(boxes[..., 2::4], 0.0, w)
        y2 = jnp.clip(boxes[..., 3::4], 0.0, h)
        out = jnp.stack([x1, y1, x2, y2], axis=-1)
        return out.reshape(boxes.shape)

    return apply_op("box_clip", _clip, input, im_info)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False, step=0.0,
                      offset=0.5):
    """SSD density prior boxes (reference:
    operators/detection/density_prior_box_op.h): per cell, a density x
    density grid of shifted centers for each (fixed_size, ratio)."""

    def _dpb(x, img, *, densities, sizes, ratios, variance, step, offset,
             clip):
        del clip  # reference clamps to [0, 1] unconditionally (max/min)
        H, W = x.shape[2], x.shape[3]
        img_h, img_w = img.shape[2], img.shape[3]
        step_w = float(step) or img_w / W
        step_h = float(step) or img_h / H
        # reference density_prior_box_op.h: sub-centers tile the STRIDE
        # cell (step_average/density shifts), not the box size
        step_average = int(0.5 * (step_w + step_h))
        boxes = []
        for size, density in zip(sizes, densities):
            shift = int(step_average / density)
            for ratio in ratios:
                bw = size * np.sqrt(ratio)
                bh = size / np.sqrt(ratio)
                base = -step_average / 2.0 + shift / 2.0
                for di in range(density):
                    for dj in range(density):
                        boxes.append((bw, bh, base + dj * shift,
                                      base + di * shift))
        A = len(boxes)
        params = jnp.asarray(boxes, jnp.float32)  # [A, 4]
        xs = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
        ys = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
        cx = xs[None, :, None] + params[None, None, :, 2]
        cy = ys[:, None, None] + params[None, None, :, 3]
        bw = jnp.broadcast_to(params[None, None, :, 0], (H, W, A))
        bh = jnp.broadcast_to(params[None, None, :, 1], (H, W, A))
        out = jnp.stack([(cx - bw / 2.0) / img_w, (cy - bh / 2.0) / img_h,
                         (cx + bw / 2.0) / img_w, (cy + bh / 2.0) / img_h],
                        axis=-1)
        out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               (H, W, A, 4))
        return out, var

    return apply_op(
        "density_prior_box", _dpb, input, image,
        densities=tuple(int(d) for d in densities),
        sizes=tuple(float(s) for s in fixed_sizes),
        ratios=tuple(float(r) for r in fixed_ratios),
        variance=tuple(float(v) for v in variance),
        step=float(step), offset=float(offset), clip=bool(clip))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False):
    """Matrix NMS (reference: operators/detection/matrix_nms_op.cc,
    SOLOv2): instead of hard suppression, each box's score decays by the
    strongest higher-scored overlap — fully dense, traceable, no
    data-dependent shapes until the final host-side filter.

    bboxes [N, 4]; scores [C, N]. Returns [M, 6] rows
    (class, score, x1, y1, x2, y2) sorted by decayed score (eager)."""
    if in_trace():
        raise errors.UnimplementedError(
            "matrix_nms output shape is data-dependent (eager only)")

    def _np_iou(bb, off):
        # host-side pairwise IoU: this whole op is eager numpy, so a
        # device round-trip per class (and per distinct box count, each
        # an XLA compile) would dominate the op
        x1, y1, x2, y2 = bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3]
        area = (x2 - x1 + off) * (y2 - y1 + off)
        ix1 = np.maximum(x1[:, None], x1[None, :])
        iy1 = np.maximum(y1[:, None], y1[None, :])
        ix2 = np.minimum(x2[:, None], x2[None, :])
        iy2 = np.minimum(y2[:, None], y2[None, :])
        inter = np.clip(ix2 - ix1 + off, 0, None) * \
            np.clip(iy2 - iy1 + off, 0, None)
        return inter / np.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)

    b = np.asarray(bboxes._value if isinstance(bboxes, Tensor) else bboxes)
    s = np.asarray(scores._value if isinstance(scores, Tensor) else scores)
    out_rows = []
    out_index = []
    for c in range(s.shape[0]):
        if c == background_label:
            continue
        cls_scores = s[c]
        keep = cls_scores > score_threshold
        if not keep.any():
            continue
        idx = np.where(keep)[0]
        order = idx[np.argsort(-cls_scores[idx])]
        if nms_top_k > 0:
            order = order[:nms_top_k]
        bb = b[order]
        sc = cls_scores[order]
        n = len(order)
        iou = _np_iou(bb.astype(np.float32), 0.0 if normalized else 1.0)
        tri = np.triu(iou, k=1)          # tri[i, j] = iou(i, j), i < j
        # SOLOv2 matrix NMS (reference matrix_nms_op.cc): each box j is
        # decayed by min over suppressors i<j of f(iou_ij)/f(comp_i),
        # where comp_i is i's own strongest suppressor overlap
        comp = np.concatenate([[0.0], tri[:, 1:].max(axis=0)]) \
            if n > 1 else np.zeros(n)    # comp[i] = max_{k<i} iou(k, i)
        if use_gaussian:
            # reference matrix_nms_op.cc: exp((comp^2 - iou^2) * sigma)
            decay_mat = np.exp((comp[:, None] ** 2 - tri ** 2)
                               * gaussian_sigma)
        else:
            decay_mat = (1.0 - tri) / np.maximum(1.0 - comp[:, None],
                                                 1e-10)
        # only i<j entries are real suppressor terms
        decay_mat = np.where(np.triu(np.ones((n, n), bool), k=1),
                             decay_mat, np.inf)
        decay = np.minimum(decay_mat.min(axis=0), 1.0) if n > 1 else \
            np.ones(n)
        decayed = sc * decay
        ok = decayed > post_threshold
        for i in np.where(ok)[0]:
            out_rows.append((float(c), float(decayed[i]), *bb[i].tolist()))
            out_index.append(int(order[i]))
    ranking = sorted(range(len(out_rows)), key=lambda k: -out_rows[k][1])
    if keep_top_k > 0:
        ranking = ranking[:keep_top_k]
    rows = [out_rows[k] for k in ranking]
    result = Tensor(np.asarray(rows, np.float32).reshape(-1, 6)
                    if rows else np.zeros((0, 6), np.float32))
    if return_index:
        index = Tensor(np.asarray([out_index[k] for k in ranking],
                                  np.int64))
        return result, index
    return result


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """Assign RoIs to FPN levels by scale (reference:
    operators/detection/distribute_fpn_proposals_op.h): level =
    floor(refer_level + log2(sqrt(area)/refer_scale)), clipped. Eager
    (outputs are per-level variable-length lists)."""
    if in_trace():
        raise errors.UnimplementedError(
            "distribute_fpn_proposals outputs are variable-length "
            "(eager only)")
    rois = np.asarray(fpn_rois._value if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    w = np.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = np.sqrt(w * h)
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois = []
    restore_parts = []
    for level in range(min_level, max_level + 1):
        idx = np.where(lvl == level)[0]
        multi_rois.append(Tensor(rois[idx].astype(np.float32)))
        restore_parts.append(idx)
    order = np.concatenate(restore_parts) if restore_parts else \
        np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return multi_rois, Tensor(restore.astype(np.int64))


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None):
    """Merge per-level proposals and keep the global top-k by score
    (reference: operators/detection/collect_fpn_proposals_op.h)."""
    if in_trace():
        raise errors.UnimplementedError(
            "collect_fpn_proposals output is top-k variable (eager only)")
    rois = np.concatenate([np.asarray(r._value if isinstance(r, Tensor)
                                      else r).reshape(-1, 4)
                           for r in multi_rois]) if multi_rois else \
        np.zeros((0, 4), np.float32)
    scores = np.concatenate([np.asarray(s._value if isinstance(s, Tensor)
                                        else s).reshape(-1)
                             for s in multi_scores]) if multi_scores else \
        np.zeros(0, np.float32)
    order = np.argsort(-scores)[:post_nms_top_n]
    return Tensor(rois[order].astype(np.float32))


def _deform_sample(x, py, px, dg):
    """Bilinear-sample x [B,C,H,W] at per-(def group, tap, out pos)
    fractional coords py/px [B,dg,K,Ho,Wo] -> [B,C,K,Ho,Wo].

    Border semantics follow the reference im2col
    (operators/math/deformable_im2col / modulated_deformable_im2col):
    each corner contributes only while it lies inside the feature map,
    so a point sliding off the edge fades to zero.
    """
    b, c, h, w = x.shape
    cpg = c // dg
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    parts = []
    for dy, dx in ((0, 0), (0, 1), (1, 0), (1, 1)):
        yc = y0 + dy
        xc = x0 + dx
        wgt = ((1 - jnp.abs(py - yc)) * (1 - jnp.abs(px - xc)))
        valid = ((yc >= 0) & (yc <= h - 1) & (xc >= 0) & (xc <= w - 1))
        wgt = jnp.where(valid, wgt, 0.0)
        yi = jnp.clip(yc, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xc, 0, w - 1).astype(jnp.int32)
        flat = yi * w + xi                       # [B,dg,K,Ho,Wo]
        # one gather per channel block: repeat the dg axis out to C
        flat_c = jnp.repeat(flat, cpg, axis=1)   # [B,C,K,Ho,Wo]
        wgt_c = jnp.repeat(wgt, cpg, axis=1)
        xf = x.reshape(b, c, h * w)
        gathered = jnp.take_along_axis(
            xf[:, :, None, :], flat_c.reshape(b, c, -1)[:, :, None, :],
            axis=-1)[:, :, 0, :].reshape(flat_c.shape)
        parts.append(gathered * wgt_c.astype(x.dtype))
    return parts[0] + parts[1] + parts[2] + parts[3]


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1 (mask=None) / v2 (modulated).

    reference: operators/deformable_conv_op.cc +
    operators/math/deformable_im2col.(cc|h); python API
    python/paddle/vision/ops.py:394 (deform_conv2d).

    TPU-native design: instead of the reference's im2col scratch +
    GEMM per image, the sampled taps are built with vectorized bilinear
    gathers ([B, C, kH*kW, Ho, Wo]) and contracted with the kernel in
    one einsum, which XLA maps onto the MXU. offset channels are
    ordered (y, x) per tap like the reference kernel:
    offset[:, 2*(g*K + k)] is Δy for def-group g, tap k.
    """
    from ..nn.functional import _pair, _norm_padding

    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _norm_padding(padding, 2)
    pads = [(p, p) if isinstance(p, int) else tuple(p) for p in pad]

    def _deform(x, offset, mask_arr, w, b, *, stride, pads, dilation,
                dg, groups):
        bsz, c, h, wdt = x.shape
        cout, cpg_w, kh, kw = w.shape
        k = kh * kw
        ho = (h + pads[0][0] + pads[0][1]
              - dilation[0] * (kh - 1) - 1) // stride[0] + 1
        wo = (wdt + pads[1][0] + pads[1][1]
              - dilation[1] * (kw - 1) - 1) // stride[1] + 1
        # base sampling grid p0 + p_k (tap offsets), then learned Δ
        iy = jnp.arange(ho) * stride[0] - pads[0][0]
        ix = jnp.arange(wo) * stride[1] - pads[1][0]
        ky, kx = jnp.meshgrid(jnp.arange(kh) * dilation[0],
                              jnp.arange(kw) * dilation[1], indexing="ij")
        base_y = (iy[None, :, None] + ky.reshape(-1)[:, None, None])
        base_x = (ix[None, None, :] + kx.reshape(-1)[:, None, None])
        off = offset.reshape(bsz, dg, k, 2, ho, wo)
        py = base_y[None, None] + off[:, :, :, 0]
        px = base_x[None, None] + off[:, :, :, 1]
        sampled = _deform_sample(x, py, px, dg)   # [B,C,K,Ho,Wo]
        if mask_arr is not None:
            m = jnp.repeat(mask_arr.reshape(bsz, dg, k, ho, wo),
                           c // dg, axis=1)
            sampled = sampled * m.astype(sampled.dtype)
        # grouped contraction: out group g uses in-channel block g
        sampled = sampled.reshape(bsz, groups, c // groups, k, ho, wo)
        wg = w.reshape(groups, cout // groups, cpg_w, kh * kw)
        y = jnp.einsum("bgckhw,gock->bgohw", sampled, wg)
        y = y.reshape(bsz, cout, ho, wo)
        if b is not None:
            y = y + b.reshape(1, -1, 1, 1)
        return y

    return apply_op("deform_conv2d", _deform, x, offset, mask, weight,
                    bias, stride=stride, pads=tuple(pads),
                    dilation=dilation, dg=int(deformable_groups),
                    groups=int(groups))


def _nn():
    from .. import nn

    return nn


class DeformConv2D(_nn().Layer):
    """Deformable conv layer (reference: python/paddle/vision/ops.py:598
    DeformConv2D): holds weight [out, in/groups, kH, kW] (+ bias) and
    applies ``deform_conv2d``; forward(x, offset, mask=None) — mask=None
    is v1, a mask tensor is v2 (modulated)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        nn = _nn()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        bound = 1.0 / np.sqrt(in_channels // groups * ks[0] * ks[1])
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            default_initializer=nn.initializer.Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True,
            default_initializer=nn.initializer.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, bias=self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups,
            groups=self._groups, mask=mask)
