"""Transforms (reference: python/paddle/vision/transforms/) — numpy-based
(CHW float arrays), no PIL/cv2 dependency."""
import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


def _chw(img):
    if img.ndim == 2:
        return img[None]
    if img.shape[0] in (1, 3, 4):
        return img
    return np.transpose(img, (2, 0, 1))


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        return Tensor(img.astype(np.float32))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def _apply_image(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img, np.float32)
        arr = _chw(arr)
        return (arr - self.mean) / self.std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        c, h, w = img.shape
        oh, ow = self.size
        ys = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
        return img[:, ys][:, :, xs]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        c, h, w = img.shape
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[:, i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            img = np.pad(img, ((0, 0), (p, p), (p, p)))
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if np.random.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        c, h, w = img.shape
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = img[:, i:i + th, j:j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img * alpha, 0, img.max())


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, int) else padding[0]

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        p = self.padding
        return np.pad(img, ((0, 0), (p, p), (p, p)))


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _chw(np.asarray(img))[:, :, ::-1].copy()


def vflip(img):
    return _chw(np.asarray(img))[:, ::-1].copy()
