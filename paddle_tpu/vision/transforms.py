"""Transforms (reference: python/paddle/vision/transforms/) — numpy-based
(CHW float arrays), no PIL/cv2 dependency."""
import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))

    def _apply_image(self, img):
        raise NotImplementedError


def _chw(img):
    if img.ndim == 2:
        return img[None]
    if img.shape[0] in (1, 3, 4):
        return img
    return np.transpose(img, (2, 0, 1))


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        return Tensor(img.astype(np.float32))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def _apply_image(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img, np.float32)
        arr = _chw(arr)
        return (arr - self.mean) / self.std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        c, h, w = img.shape
        oh, ow = self.size
        ys = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
        return img[:, ys][:, :, xs]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        c, h, w = img.shape
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[:, i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            img = np.pad(img, ((0, 0), (p, p), (p, p)))
        c, h, w = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if np.random.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        c, h, w = img.shape
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = img[:, i:i + th, j:j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


def _jitter_range(value, name, center=1.0):
    """Normalize a jitter knob to a (lo, hi) sample range (reference
    transforms.py _check_input): a float v maps to
    [max(0, center-v), center+v]; a (lo, hi) pair is used directly."""
    if isinstance(value, (list, tuple)):
        lo, hi = float(value[0]), float(value[1])
        if lo > hi or lo < 0:
            raise ValueError(f"{name} range must satisfy 0 <= lo <= hi, "
                             f"got {value}")
        return lo, hi
    if value < 0:
        raise ValueError(f"{name} value must be non-negative")
    return max(0.0, center - float(value)), center + float(value)


def _ceiling(img):
    """Value ceiling for clipping: integer images (uint8 PIL/ndarray) live
    in [0, 255] by dtype; for floats the value heuristic is the only
    signal left, so a dark [0, 255]-float image must be passed here
    BEFORE any float32 conversion of an integer original."""
    img = np.asarray(img)
    if np.issubdtype(img.dtype, np.integer):
        return 255.0
    return 255.0 if img.size and img.max() > 1.5 else 1.0


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.range = _jitter_range(value, "brightness")

    def _apply_image(self, img):
        raw = np.asarray(img)
        img = raw.astype(np.float32)
        return np.clip(img * np.random.uniform(*self.range), 0,
                       _ceiling(raw))


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, int) else padding[0]

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        p = self.padding
        return np.pad(img, ((0, 0), (p, p), (p, p)))


def _gray(img):
    """Luminance over a CHW image (Rec.601 weights, reference:
    transforms/functional_tensor.py to_grayscale)."""
    if img.shape[0] == 1:
        return img[0]
    w = np.asarray([0.299, 0.587, 0.114], np.float32)
    return np.tensordot(w, img[:3].astype(np.float32), axes=1)


class ContrastTransform(BaseTransform):
    """reference: transforms.py:737 — blend with the mean gray level."""

    def __init__(self, value, keys=None):
        self.range = _jitter_range(value, "contrast")

    def _apply_image(self, img):
        raw = np.asarray(img)
        img = _chw(raw.astype(np.float32))
        alpha = np.random.uniform(*self.range)
        mean = _gray(img).mean()
        return np.clip(alpha * img + (1 - alpha) * mean, 0, _ceiling(raw))


class SaturationTransform(BaseTransform):
    """reference: transforms.py:775 — blend with per-pixel grayscale."""

    def __init__(self, value, keys=None):
        self.range = _jitter_range(value, "saturation")

    def _apply_image(self, img):
        raw = np.asarray(img)
        img = _chw(raw.astype(np.float32))
        alpha = np.random.uniform(*self.range)
        gray = _gray(img)[None]
        return np.clip(alpha * img + (1 - alpha) * gray, 0, _ceiling(raw))


def _rgb_to_hsv(img):
    """img: [3, H, W] in [0, 1] -> h, s, v arrays."""
    r, g, b = img
    maxc = np.max(img, axis=0)
    minc = np.min(img, axis=0)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    safe = np.maximum(delta, 1e-12)
    h = np.where(maxc == r, (g - b) / safe % 6,
                 np.where(maxc == g, (b - r) / safe + 2,
                          (r - g) / safe + 4)) / 6.0
    return np.where(delta == 0, 0.0, h), s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int64) % 6
    choices = [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v),
               (v, p, q)]
    r = np.select([i == k for k in range(6)], [c[0] for c in choices])
    g = np.select([i == k for k in range(6)], [c[1] for c in choices])
    b = np.select([i == k for k in range(6)], [c[2] for c in choices])
    return np.stack([r, g, b]).astype(np.float32)


class HueTransform(BaseTransform):
    """reference: transforms.py:811 — shift hue in HSV space."""

    def __init__(self, value, keys=None):
        if isinstance(value, (list, tuple)):
            lo, hi = float(value[0]), float(value[1])
            if not -0.5 <= lo <= hi <= 0.5:
                raise ValueError(f"hue range must be within [-0.5, 0.5], "
                                 f"got {value}")
            self.range = (lo, hi)
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value must be in [0, 0.5]")
            self.range = (-float(value), float(value))

    def _apply_image(self, img):
        raw = np.asarray(img)
        img = _chw(raw.astype(np.float32))
        if img.shape[0] == 1:
            return img
        scale = _ceiling(raw)
        h, s, v = _rgb_to_hsv(img[:3] / scale)
        shift = np.random.uniform(*self.range)
        out = _hsv_to_rgb((h + shift) % 1.0, s, v) * scale
        return np.concatenate([out, img[3:]]) if img.shape[0] > 3 else out


class ColorJitter(BaseTransform):
    """reference: transforms.py:848 — random-order composition of
    brightness/contrast/saturation/hue perturbations."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.parts = []
        if brightness:
            self.parts.append(BrightnessTransform(brightness))
        if contrast:
            self.parts.append(ContrastTransform(contrast))
        if saturation:
            self.parts.append(SaturationTransform(saturation))
        if hue:
            self.parts.append(HueTransform(hue))

    def _apply_image(self, img):
        for k in np.random.permutation(len(self.parts)):
            img = self.parts[k]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    """reference: transforms.py:1176."""

    def __init__(self, num_output_channels=1, keys=None):
        if num_output_channels not in (1, 3):
            raise ValueError("num_output_channels must be 1 or 3")
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        g = _gray(img.astype(np.float32))[None]
        if self.num_output_channels == 3:
            g = np.repeat(g, 3, axis=0)
        return g.astype(img.dtype) if img.dtype == np.uint8 else g


class RandomRotation(BaseTransform):
    """reference: transforms.py:1090 — rotate by a random angle in
    ``degrees`` about the center (nearest-neighbor resampling,
    expand=False semantics: output keeps the input size)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            self.degrees = (-degrees, degrees)
        else:
            self.degrees = tuple(degrees)
        self.fill = fill

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        c, h, w = img.shape
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.mgrid[0:h, 0:w]
        # inverse mapping: sample source = R(-angle) @ (dst - center)
        cos, sin = np.cos(angle), np.sin(angle)
        sy = cos * (yy - cy) - sin * (xx - cx) + cy
        sx = sin * (yy - cy) + cos * (xx - cx) + cx
        syi = np.round(sy).astype(np.int64)
        sxi = np.round(sx).astype(np.int64)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full_like(img, self.fill)
        out[:, valid] = img[:, syi[valid], sxi[valid]]
        return out


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _chw(np.asarray(img))[:, :, ::-1].copy()


def vflip(img):
    return _chw(np.asarray(img))[:, ::-1].copy()


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def adjust_brightness(img, brightness_factor):
    img = _chw(np.asarray(img, np.float32))
    return np.clip(img * brightness_factor, 0,
                   255.0 if img.max() > 1.5 else 1.0)


def adjust_contrast(img, contrast_factor):
    img = _chw(np.asarray(img, np.float32))
    mean = _gray(img).mean()
    return np.clip(contrast_factor * img + (1 - contrast_factor) * mean, 0,
                   255.0 if img.max() > 1.5 else 1.0)


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    img = _chw(np.asarray(img, np.float32))
    if img.shape[0] == 1:
        return img
    scale = 255.0 if img.max() > 1.5 else 1.0
    h, s, v = _rgb_to_hsv(img[:3] / scale)
    return _hsv_to_rgb((h + hue_factor) % 1.0, s, v) * scale


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    t = RandomRotation((angle, angle), fill=fill)
    return t._apply_image(np.asarray(img))


from ..core.module_alias import alias_submodules as _alias

_alias(__name__, "functional", "transforms")
