"""Explicit control-flow ops (reference: operators/controlflow/
conditional_block_op.cc + while_op.cc, exposed as
python/paddle/fluid/layers/control_flow.py cond:*, while_loop:*, case,
switch_case; re-exported by paddle.static.nn).

TPU-native: cond -> lax.cond, while_loop -> lax.while_loop,
switch_case -> lax.switch — compiled XLA control flow, usable both in
dygraph (concrete predicates short-circuit to Python) and under
jit/to_static (traced predicates compile).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit.dy2static import _pred_value


def _flatten_out(out, box):
    leaves, treedef = jax.tree_util.tree_flatten(
        out, is_leaf=lambda x: isinstance(x, Tensor))
    box["treedef"] = treedef
    box["is_tensor"] = [isinstance(l, Tensor) for l in leaves]
    return tuple(l._value if isinstance(l, Tensor) else jnp.asarray(l)
                 for l in leaves)


def _rebuild_out(flat, box):
    leaves = [Tensor(a, stop_gradient=True) for a in flat]
    return jax.tree_util.tree_unflatten(box["treedef"], leaves)


def _traced_select(chooser, fns):
    """Shared lax.cond/lax.switch plumbing: wrap no-arg branch callables
    into flat-array branches sharing one output skeleton."""
    box = {}

    def wrap(fn):
        def g(_):
            return _flatten_out(fn(), box)

        return g

    flat = chooser([wrap(f) for f in fns])
    return _rebuild_out(flat, box)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference: layers.cond — branch callables take no args; both must
    return structurally-identical outputs. A None branch is a no-op
    returning None (reference cond allows None when the other branch
    returns nothing)."""
    true_fn = true_fn or (lambda: None)
    false_fn = false_fn or (lambda: None)
    kind, p = _pred_value(pred)
    if kind == "py":
        return true_fn() if p else false_fn()
    return _traced_select(
        lambda fns: jax.lax.cond(p != 0, fns[0], fns[1], ()),
        [true_fn, false_fn])


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               maximum_iterations=None):
    """reference: layers.while_loop — body returns the next loop_vars list;
    shapes/dtypes must be loop-invariant (while_op contract).

    Pass `maximum_iterations` to make the traced loop reverse-mode
    differentiable: it lowers to a lax.scan of that many cond-masked steps
    (the while_grad analog — XLA cannot differentiate a dynamic trip
    count, so the bound buys the backward pass)."""
    from ..jit.dy2static import convert_while

    vals = tuple(loop_vars)
    cond_fn, body_fn = cond, body
    body = lambda *vs: tuple(body_fn(*vs))  # noqa: E731
    out = convert_while(lambda *vs: cond_fn(*vs), body, vals,
                        maximum_iterations=maximum_iterations)
    return list(out)


def case(pred_fn_pairs, default=None, name=None):
    """reference: layers.case — first matching predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: layers.switch_case -> lax.switch (native XLA multi-way)."""
    if not branch_fns:
        raise ValueError("switch_case: branch_fns must be non-empty")
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns)) if callable(branch_fns[0]) \
            else sorted(branch_fns)
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    idx_arr = branch_index._value if isinstance(branch_index, Tensor) \
        else jnp.asarray(branch_index)
    idx_arr = jnp.squeeze(idx_arr)

    dense = keys == list(range(len(keys)))
    traced = isinstance(idx_arr, jax.core.Tracer)
    if not traced:
        i = int(idx_arr)
        if i in keys:
            return fns[keys.index(i)]()
        if default is not None:
            return default()
        return fns[-1]()  # reference falls back to the max-key branch

    branch_list = list(fns) + ([default] if default is not None else [])
    default_pos = len(branch_list) - 1
    if dense:
        pos = jnp.clip(idx_arr, 0, len(fns) - 1)
        pos = jnp.where((idx_arr >= 0) & (idx_arr < len(fns)), pos,
                        default_pos)
    else:
        pos = jnp.asarray(default_pos)
        for j, k in enumerate(keys):
            pos = jnp.where(idx_arr == k, j, pos)
    return _traced_select(lambda wrapped: jax.lax.switch(pos, wrapped, ()),
                          branch_list)
