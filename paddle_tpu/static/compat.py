"""Static-graph compat surface (reference: python/paddle/static/
__init__.py exports backed by fluid/compiler.py CompiledProgram,
framework/parallel_executor.cc, fluid/io.py save/load,
fluid/layers/nn.py py_func / Print).

Design stance (SURVEY §7): BuildStrategy/ExecutionStrategy tuned the
reference's SSA-graph executors; XLA performs those passes (fusion,
memory planning, scheduling) automatically, so here they are accepted,
validated config carriers and CompiledProgram/ParallelExecutor are thin
aliases over the jit-compiling Executor — the documented, not silent,
delegation."""
import os

import numpy as np

from ..core.tensor import Parameter, Tensor

__all__ = [
    "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
    "ParallelExecutor", "Print", "Variable", "create_global_var",
    "save", "load", "load_program_state", "set_program_state", "py_func",
]

# a static-graph "Variable" IS a recorded Tensor in this design
Variable = Tensor


class BuildStrategy:
    """reference: framework/details/build_strategy.h. Every knob is a
    plain attribute; XLA's compiler performs the corresponding passes
    (fusion, memory reuse, allreduce fusion) unconditionally, so the
    knobs carry intent for API compat rather than toggling behavior.
    Setting a SEMANTIC knob away from its default (reduce_strategy,
    gradient_scale_strategy) warns once — ported code that depends on
    those semantics should hear that XLA decides them, not silence."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    # knobs whose non-default value would CHANGE numerics/semantics in
    # the reference (the pure perf-hint knobs stay silent: XLA fuses /
    # reuses memory unconditionally)
    _SEMANTIC_DEFAULTS = {
        "reduce_strategy": 0,
        "gradient_scale_strategy": 0,
    }

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_broadcast_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_all_optimizer_ops = False
        self.fuse_all_reduce_ops = False
        self.memory_optimize = None
        self.enable_inplace = True
        self.build_cuda_graph = False
        self.num_trainers = 1
        self.trainer_id = 0

    def __setattr__(self, name, value):
        default = self._SEMANTIC_DEFAULTS.get(name)
        if default is not None and value != default:
            import warnings

            warnings.warn(
                f"BuildStrategy.{name}={value!r} is a no-op on TPU: XLA "
                "chooses the reduction/fusion schedule; gradient scaling "
                "follows the optimizer config (spmd.build_train_step)",
                stacklevel=2)
        object.__setattr__(self, name, value)


class ExecutionStrategy:
    """reference: framework/details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    """reference: fluid/compiler.py CompiledProgram. Executor.run accepts
    it transparently — compilation happens in the jit cache either way;
    with_data_parallel records the intent (XLA shards over the mesh)."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._loss_name = None
        self._is_data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._loss_name = loss_name
        self._build_strategy = build_strategy or self._build_strategy
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._is_data_parallel = True
        return self


class ParallelExecutor:
    """reference: framework/parallel_executor.cc (legacy API). Thin
    facade: one XLA executable spans all local devices."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .program import Executor, default_main_program

        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._executor = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        from .program import default_main_program

        program = self._program or default_main_program()
        names = list(fetch_list or [])
        resolved = [program.var(n) if isinstance(n, str) else n
                    for n in names]
        return self._executor.run(program, feed=feed,
                                  fetch_list=resolved,
                                  return_numpy=return_numpy)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: fluid/layers/control_flow.py Print op — runtime tensor
    printing that survives jit (jax.debug.print rides the XLA program)."""
    import jax

    from ..core.dispatch import apply_op

    msg = message or "Print"

    def _print(x, *, msg):
        # jax.debug.callback instead of debug.print: the message is
        # arbitrary user text (braces would be parsed as format fields,
        # and jax's escaped-brace handling is broken)
        def host(v):
            print(msg, v)

        jax.debug.callback(host, x)
        return x

    return apply_op("print_op", _print, input, msg=str(msg))


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: fluid/layers/tensor.py create_global_var — a
    persistable filled variable living in the global scope."""
    from .program import global_scope

    arr = np.full(shape, value, dtype=np.dtype(dtype)
                  if dtype != "bfloat16" else np.float32)
    var = Parameter(arr, name=name)
    var.persistable = True
    var.stop_gradient = False
    scope = global_scope()
    scope.vars[var.name or f"global_var_{id(var)}"] = var
    return var


def _named_params(program):
    """Stable name→param map: explicit names where set, else positional
    (all_parameters() order is the recording order, deterministic for a
    given program build)."""
    out = {}
    for i, p in enumerate(program.all_parameters()):
        out[p.name or f"param_{i}"] = p
    return out


def _param_state(program):
    return {name: np.asarray(p._value)
            for name, p in _named_params(program).items()}


def save(program, model_path, protocol=4, **configs):
    """reference: fluid/io.py static save — parameters to
    ``model_path + '.pdparams'``."""
    state = _param_state(program)
    path = model_path + ".pdparams"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **state)
    # numpy appends .npz; normalize to the paddle extension
    os.replace(path + ".npz", path)
    return path


def load(program, model_path, executor=None, var_list=None):
    """reference: fluid/io.py static load."""
    set_program_state(program, load_program_state(model_path,
                                                  var_list=var_list))


def load_program_state(model_path, var_list=None):
    """reference: fluid/io.py load_program_state — dict name→ndarray."""
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    with np.load(path) as data:
        state = {k: data[k] for k in data.files}
    if var_list is not None:
        keep = {getattr(v, "name", v) for v in var_list}
        state = {k: v for k, v in state.items() if k in keep}
    return state


def set_program_state(program, state_dict):
    """reference: fluid/io.py set_program_state."""
    by_name = _named_params(program)
    missing = []
    for name, arr in state_dict.items():
        p = by_name.get(name)
        if p is None:
            missing.append(name)
            continue
        p._value = np.asarray(arr)
    if missing:
        raise ValueError(f"state entries not found in program: {missing}")


_PYFUNC_UIDS = None  # weak func -> {sig: (uid, weak backward_func)} — lazy
_PYFUNC_COUNTER = [0]


def _pyfunc_uid(func, backward_func, sig):
    """Stable per-(func, backward_func, call signature) uid for the
    jit-cache key.

    id() is NOT usable here: CPython reuses addresses after GC, so a
    fresh lambda could silently hit a dead lambda's cached jit (whose
    callback closure still calls the OLD function). ``sig`` — the
    (output templates, input avals, skip config) the closure bakes in —
    must also discriminate: the same func called at new shapes or with
    a different skip set needs a fresh jit, not the stale closure.
    Every (func, sig) keeps its OWN uid so alternating shapes (e.g. a
    partial last batch) stay warm instead of evict-thrashing; a changed
    backward for the same sig replaces that entry (its jits evicted).
    func death evicts everything via weak-registry finalizers."""
    global _PYFUNC_UIDS
    import weakref

    from ..core.dispatch import evict_ops

    if _PYFUNC_UIDS is None:
        _PYFUNC_UIDS = weakref.WeakKeyDictionary()
    per_sig = _PYFUNC_UIDS.setdefault(func, {})
    rec = per_sig.get(sig)
    if rec is not None:
        uid, bwd_ref = rec
        if (backward_func is None) == (bwd_ref is None) and (
                bwd_ref is None or bwd_ref() is backward_func):
            return uid
        # same shapes, different backward: replace this entry's jits
        for nm in (f"py_func_u{uid}", f"py_func_bwd_u{uid}"):
            evict_ops(nm)
    _PYFUNC_COUNTER[0] += 1
    uid = _PYFUNC_COUNTER[0]
    per_sig[sig] = (
        uid, None if backward_func is None else weakref.ref(backward_func))
    for nm in (f"py_func_u{uid}", f"py_func_bwd_u{uid}"):
        weakref.finalize(func, evict_ops, nm)
    return uid


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: fluid/layers/nn.py py_func / operators/py_func_op.cc —
    run arbitrary Python in the graph. Mapped to jax.pure_callback (host
    callback inside the XLA program); ``out`` provides the result
    template(s). ``backward_func`` follows the reference contract: it is
    called with (forward inputs..., forward outputs..., output grads...)
    — minus any variables listed in ``skip_vars_in_backward_input`` —
    and must return one gradient per forward input (None for
    non-differentiable inputs). Wired through jax.custom_vjp so it runs
    inside compiled backward passes too."""
    import jax

    from ..core.dispatch import apply_op

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    templates = tuple(
        jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(str(o.dtype))
                             if str(o.dtype) != "bfloat16" else np.float32)
        for o in outs)

    def host(*vals):
        res = func(*[Tensor(np.asarray(v)) for v in vals])
        rs = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r._value if isinstance(r, Tensor)
                                else r) for r in rs)

    def _py_fwd_callback(*arrs):
        return jax.pure_callback(host, templates, *arrs)

    skip = set(id(v) for v in (skip_vars_in_backward_input or []))
    keep_x = [i for i, v in enumerate(xs) if id(v) not in skip]
    keep_o = [i for i, v in enumerate(outs) if id(v) not in skip]
    # keep the REAL dtype (incl. bfloat16 via ml_dtypes): custom_vjp
    # validates that bwd cotangents match the primal avals
    in_templates = tuple(
        jax.ShapeDtypeStruct(tuple(v._value.shape), v._value.dtype)
        for v in xs)

    # the callbacks capture func/backward_func AND the templates/skip
    # config: the op name must discriminate all of it, or a second call
    # with the same funcs at new shapes would reuse a stale closure
    sig = (tuple((t.shape, str(t.dtype)) for t in templates),
           tuple((t.shape, str(t.dtype)) for t in in_templates),
           tuple(keep_x), tuple(keep_o))
    uid = _pyfunc_uid(func, backward_func, sig)
    if backward_func is None:
        result = apply_op(f"py_func_u{uid}", _py_fwd_callback, *xs)
    else:

        def host_bwd(*vals):
            res = backward_func(*[Tensor(np.asarray(v)) for v in vals])
            rs = res if isinstance(res, (list, tuple)) else [res]
            grads = []
            for t, r in zip(in_templates, rs):
                if r is None:
                    grads.append(np.zeros(t.shape, t.dtype))
                else:
                    a = np.asarray(r._value if isinstance(r, Tensor)
                                   else r)
                    grads.append(a.astype(t.dtype, copy=False))
            return tuple(grads)

        @jax.custom_vjp
        def _py(*arrs):
            return _py_fwd_callback(*arrs)

        def _fwd(*arrs):
            res = _py(*arrs)
            saved = res if isinstance(res, tuple) else (res,)
            return res, (arrs, saved)

        def _bwd(saved, g):
            arrs, outs_v = saved
            gs = g if isinstance(g, tuple) else (g,)
            call_ins = ([arrs[i] for i in keep_x]
                        + [outs_v[i] for i in keep_o] + list(gs))
            return jax.pure_callback(host_bwd, in_templates, *call_ins)

        _py.defvjp(_fwd, _bwd)
        result = apply_op(f"py_func_bwd_u{uid}", _py, *xs)
    results = result if isinstance(result, (list, tuple)) else [result]
    for o, r in zip(outs, results):
        # transplant value AND tape linkage onto the caller's template
        # tensors (the reference returns `out`; gradients must flow
        # through the object the user holds)
        o._assign_result(r)
    return out


# ------------------------------------------------ serialization family
# (reference: static/io.py serialize_program/serialize_persistables/
# deserialize_* / save_to_file / load_from_file — protobuf bytes there,
# the StableHLO+pdiparams artifact bytes here)


# serialized blobs use a length-prefixed tagged container; the payloads
# inside are themselves pickle-free (StableHLO bytes, json meta, npz
# params loaded with allow_pickle=False), so untrusted model bytes can
# fail to parse but cannot execute code. Layout: magic, then per entry a
# json-encoded {"ext", "size"} header line + raw bytes.
_SER_MAGIC = b"PDTPU1\n"


def _pack(blob):
    import json as _json

    out = [_SER_MAGIC]
    for ext, data in blob.items():
        out.append(_json.dumps({"ext": ext, "size": len(data)})
                   .encode() + b"\n")
        out.append(data)
    return b"".join(out)


def _unpack(data):
    import json as _json

    if not data.startswith(_SER_MAGIC):
        raise ValueError("not a paddle_tpu serialized artifact")
    pos = len(_SER_MAGIC)
    blob = {}
    while pos < len(data):
        nl = data.index(b"\n", pos)
        head = _json.loads(data[pos:nl].decode())
        pos = nl + 1
        blob[head["ext"]] = data[pos:pos + head["size"]]
        pos += head["size"]
    return blob


def _export_artifacts(feed_vars, fetch_vars, program):
    """Export and read every artifact into memory, cleaning up the temp
    dir. Deliberately uncached: params live in mutable Tensors, so any
    cache key short of hashing every weight would serve stale bytes
    after a training step (checkpointing the wrong weights silently)."""
    import shutil
    import tempfile

    from .program import default_main_program, save_inference_model

    program = program or default_main_program()
    d = tempfile.mkdtemp(prefix="pdtpu_ser_")
    try:
        prefix = os.path.join(d, "model")
        save_inference_model(prefix, list(feed_vars), list(fetch_vars),
                             None, program=program)
        blob = {}
        for ext in (".pdmodel", ".pdmeta.json", ".pdiparams"):
            p = prefix + ext
            if os.path.exists(p):
                with open(p, "rb") as f:
                    blob[ext] = f.read()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return blob


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Portable program bytes (StableHLO + meta, no params)."""
    blob = _export_artifacts(feed_vars, fetch_vars, program)
    return _pack({k: v for k, v in blob.items() if k != ".pdiparams"})


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs):
    """Parameter bytes matching serialize_program's artifact."""
    blob = _export_artifacts(feed_vars, fetch_vars, program)
    return _pack({".pdiparams": blob[".pdiparams"]})


class _DeserializedProgram:
    """Callable handle over deserialized artifacts; params attach via
    deserialize_persistables. Run it directly, or through
    Executor.run(feed=..., fetch_list=None) duck-typing."""

    def __init__(self, blob):
        import shutil
        import tempfile
        import weakref

        self._dir = tempfile.mkdtemp(prefix="pdtpu_deser_")
        self._prefix = os.path.join(self._dir, "model")
        weakref.finalize(self, shutil.rmtree, self._dir,
                         ignore_errors=True)
        self._write(blob)
        self.layer = None

    def _write(self, blob):
        for ext, data in blob.items():
            with open(self._prefix + ext, "wb") as f:
                f.write(data)

    def _load(self):
        from ..jit import load as jit_load

        self.layer = jit_load(self._prefix)
        return self.layer

    def __call__(self, *inputs):
        if self.layer is None:
            raise RuntimeError(
                "deserialize_persistables must attach parameters before "
                "running the program")
        return self.layer(*inputs)


def deserialize_program(data):
    return _DeserializedProgram(_unpack(bytes(data)))


def deserialize_persistables(program, data, executor=None):
    if not isinstance(program, _DeserializedProgram):
        raise TypeError("program must come from deserialize_program")
    program._write(_unpack(bytes(data)))
    return program._load()


def save_to_file(path, content):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference: fluid/layers/tensor.py create_parameter."""
    from ..nn import initializer as I

    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierNormal())
    if attr is not None:
        name = name or getattr(attr, "name", None)
        init = getattr(attr, "initializer", None) or init
    arr = np.zeros(shape, np.dtype(dtype) if dtype != "bfloat16"
                   else np.float32)
    p = Parameter(arr, name=name)
    init(p)
    return p


def accuracy(input, label, k=1, correct=None, total=None):
    """Batch top-k accuracy tensor (reference:
    fluid/layers/metric_op.py accuracy)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    def _acc(logits, y, *, k):
        topk = jnp.argsort(-logits, axis=-1)[..., :k]
        y = y.reshape(-1, 1)
        hit = (topk == y).any(axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op("accuracy", _acc, input, label, k=int(k))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC tensor via the rank statistic (reference:
    fluid/layers/metric_op.py auc — there a stateful op accumulating
    confusion bins; here the exact batch AUC, stateless under jit)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    def _auc(probs, y):
        # probability of the positive class
        p = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 \
            else probs.reshape(-1)
        y = y.reshape(-1).astype(jnp.float32)
        # tie-corrected (average) ranks: ordinal ranks would make the
        # statistic order-dependent whenever scores tie (a constant
        # predictor must score exactly 0.5)
        sorted_p = jnp.sort(p)
        lo = jnp.searchsorted(sorted_p, p, side="left")
        hi = jnp.searchsorted(sorted_p, p, side="right")
        ranks = (lo + hi + 1).astype(jnp.float32) / 2.0
        n_pos = jnp.sum(y)
        n_neg = y.shape[0] - n_pos
        sum_ranks_pos = jnp.sum(ranks * y)
        denom = jnp.maximum(n_pos * n_neg, 1.0)
        return (sum_ranks_pos - n_pos * (n_pos + 1) / 2.0) / denom

    return apply_op("auc", _auc, input, label)


def xpu_places(device_ids=None):
    raise RuntimeError(
        "xpu_places: not compiled with XPU (this is the TPU-native build; "
        "use paddle.static.tpu_places)")


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference: fluid/io.py save_vars — save a (filtered) subset of a
    program's parameters under ``dirname``."""
    from .program import default_main_program

    program = main_program or default_main_program()
    named = _named_params(program)
    if vars is not None:
        keep = {getattr(v, "name", v) for v in vars}
        named = {n: p for n, p in named.items() if n in keep}
    elif predicate is not None:
        named = {n: p for n, p in named.items() if predicate(p)}
    os.makedirs(dirname, exist_ok=True)
    target = os.path.join(dirname, filename or "vars.npz")
    np.savez(target, **{n: np.asarray(p._value) for n, p in named.items()})
    base, ext = os.path.splitext(target)
    if ext != ".npz":  # numpy always appends .npz
        os.replace(target + ".npz", target)
    return target


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference: fluid/io.py load_vars."""
    from .program import default_main_program

    program = main_program or default_main_program()
    target = os.path.join(dirname, filename or "vars.npz")
    with np.load(target) as data:
        state = {k: data[k] for k in data.files}
    named = _named_params(program)
    if vars is not None:
        keep = {getattr(v, "name", v) for v in vars}
        state = {k: v for k, v in state.items() if k in keep}
    for n, arr in state.items():
        p = named.get(n)
        if p is None or (predicate is not None and not predicate(p)):
            continue
        p._value = arr
