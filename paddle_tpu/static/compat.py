"""Static-graph compat surface (reference: python/paddle/static/
__init__.py exports backed by fluid/compiler.py CompiledProgram,
framework/parallel_executor.cc, fluid/io.py save/load,
fluid/layers/nn.py py_func / Print).

Design stance (SURVEY §7): BuildStrategy/ExecutionStrategy tuned the
reference's SSA-graph executors; XLA performs those passes (fusion,
memory planning, scheduling) automatically, so here they are accepted,
validated config carriers and CompiledProgram/ParallelExecutor are thin
aliases over the jit-compiling Executor — the documented, not silent,
delegation."""
import os

import numpy as np

from ..core.tensor import Parameter, Tensor

__all__ = [
    "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
    "ParallelExecutor", "Print", "Variable", "create_global_var",
    "save", "load", "load_program_state", "set_program_state", "py_func",
]

# a static-graph "Variable" IS a recorded Tensor in this design
Variable = Tensor


class BuildStrategy:
    """reference: framework/details/build_strategy.h. Every knob is a
    plain attribute; XLA's compiler performs the corresponding passes
    (fusion, memory reuse, allreduce fusion) unconditionally, so the
    knobs carry intent for API compat rather than toggling behavior."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_broadcast_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_all_optimizer_ops = False
        self.fuse_all_reduce_ops = False
        self.memory_optimize = None
        self.enable_inplace = True
        self.build_cuda_graph = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """reference: framework/details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    """reference: fluid/compiler.py CompiledProgram. Executor.run accepts
    it transparently — compilation happens in the jit cache either way;
    with_data_parallel records the intent (XLA shards over the mesh)."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._loss_name = None
        self._is_data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._loss_name = loss_name
        self._build_strategy = build_strategy or self._build_strategy
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._is_data_parallel = True
        return self


class ParallelExecutor:
    """reference: framework/parallel_executor.cc (legacy API). Thin
    facade: one XLA executable spans all local devices."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .program import Executor, default_main_program

        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._executor = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        from .program import default_main_program

        program = self._program or default_main_program()
        names = list(fetch_list or [])
        resolved = [program.var(n) if isinstance(n, str) else n
                    for n in names]
        return self._executor.run(program, feed=feed,
                                  fetch_list=resolved,
                                  return_numpy=return_numpy)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: fluid/layers/control_flow.py Print op — runtime tensor
    printing that survives jit (jax.debug.print rides the XLA program)."""
    import jax

    from ..core.dispatch import apply_op

    msg = message or "Print"

    def _print(x, *, msg):
        # jax.debug.callback instead of debug.print: the message is
        # arbitrary user text (braces would be parsed as format fields,
        # and jax's escaped-brace handling is broken)
        def host(v):
            print(msg, v)

        jax.debug.callback(host, x)
        return x

    return apply_op("print_op", _print, input, msg=str(msg))


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: fluid/layers/tensor.py create_global_var — a
    persistable filled variable living in the global scope."""
    from .program import global_scope

    arr = np.full(shape, value, dtype=np.dtype(dtype)
                  if dtype != "bfloat16" else np.float32)
    var = Parameter(arr, name=name)
    var.persistable = True
    var.stop_gradient = False
    scope = global_scope()
    scope.vars[var.name or f"global_var_{id(var)}"] = var
    return var


def _named_params(program):
    """Stable name→param map: explicit names where set, else positional
    (all_parameters() order is the recording order, deterministic for a
    given program build)."""
    out = {}
    for i, p in enumerate(program.all_parameters()):
        out[p.name or f"param_{i}"] = p
    return out


def _param_state(program):
    return {name: np.asarray(p._value)
            for name, p in _named_params(program).items()}


def save(program, model_path, protocol=4, **configs):
    """reference: fluid/io.py static save — parameters to
    ``model_path + '.pdparams'``."""
    state = _param_state(program)
    path = model_path + ".pdparams"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **state)
    # numpy appends .npz; normalize to the paddle extension
    os.replace(path + ".npz", path)
    return path


def load(program, model_path, executor=None, var_list=None):
    """reference: fluid/io.py static load."""
    set_program_state(program, load_program_state(model_path,
                                                  var_list=var_list))


def load_program_state(model_path, var_list=None):
    """reference: fluid/io.py load_program_state — dict name→ndarray."""
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    with np.load(path) as data:
        state = {k: data[k] for k in data.files}
    if var_list is not None:
        keep = {getattr(v, "name", v) for v in var_list}
        state = {k: v for k, v in state.items() if k in keep}
    return state


def set_program_state(program, state_dict):
    """reference: fluid/io.py set_program_state."""
    by_name = _named_params(program)
    missing = []
    for name, arr in state_dict.items():
        p = by_name.get(name)
        if p is None:
            missing.append(name)
            continue
        p._value = np.asarray(arr)
    if missing:
        raise ValueError(f"state entries not found in program: {missing}")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: fluid/layers/nn.py py_func — run arbitrary Python in
    the graph. Mapped to jax.pure_callback (host callback inside the XLA
    program); ``out`` provides the result template(s). backward_func is
    unsupported (use PyLayer for custom gradients)."""
    import jax

    from ..core.dispatch import apply_op

    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func: use paddle.autograd.PyLayer for "
            "custom gradients on TPU")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    templates = tuple(
        jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(str(o.dtype))
                             if str(o.dtype) != "bfloat16" else np.float32)
        for o in outs)

    def _py(*arrs):
        import jax.numpy as jnp

        def host(*vals):
            res = func(*[Tensor(np.asarray(v)) for v in vals])
            rs = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r._value if isinstance(r, Tensor)
                                    else r) for r in rs)

        return jax.pure_callback(host, templates, *arrs)

    result = apply_op("py_func", _py, *xs)
    results = result if isinstance(result, (list, tuple)) else [result]
    for o, r in zip(outs, results):
        o._value = r._value
    return out
