"""Additional paddle.static.nn builders (reference:
python/paddle/static/nn/__init__.py — LayerHelper-style functions that
create parameters inside the active program and apply the op). Each
builder instantiates the corresponding nn.Layer so parameter recording
rides the normal dispatch hook."""
import numpy as np


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: static/nn/common.py embedding."""
    from ..nn.layers.common import Embedding

    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32"):
    """reference: fluid/contrib/sparse_embedding — PS-backed lookup. In
    the single-program static path this builds a dense table; the PS
    path (distributed/ps.sparse_embedding) serves the huge-vocab case,
    and `entry` admission configs apply there."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def _check_nchw(kw, builder):
    fmt = kw.get("data_format", kw.get("data_layout", "NCHW"))
    if fmt not in ("NCHW", "NCDHW", "NCL"):
        raise NotImplementedError(
            f"{builder}: data_format {fmt!r} unsupported (channel-first "
            f"only; XLA canonicalizes layout on TPU anyway)")


def _apply_act(out, kw):
    act = kw.get("act")
    if act:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, **kw):
    from ..nn.layers.conv import Conv2DTranspose

    _check_nchw(kw, "conv2d_transpose")
    layer = Conv2DTranspose(input.shape[1], num_filters, filter_size,
                            stride, padding, output_padding,
                            dilation=dilation, groups=groups,
                            weight_attr=param_attr,
                            bias_attr=bias_attr)
    return _apply_act(layer(input), kw)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None, **kw):
    from ..nn.layers.conv import Conv3D

    _check_nchw(kw, "conv3d")
    layer = Conv3D(input.shape[1], num_filters, filter_size, stride,
                   padding, dilation, groups, weight_attr=param_attr,
                   bias_attr=bias_attr)
    return _apply_act(layer(input), kw)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, **kw):
    from ..nn.layers.conv import Conv3DTranspose

    _check_nchw(kw, "conv3d_transpose")
    layer = Conv3DTranspose(input.shape[1], num_filters, filter_size,
                            stride, padding, output_padding,
                            dilation=dilation, groups=groups,
                            weight_attr=param_attr,
                            bias_attr=bias_attr)
    return _apply_act(layer(input), kw)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, **kw):
    from ..nn.layers.norm import LayerNorm

    shape = list(input.shape[begin_norm_axis:])
    layer = LayerNorm(shape, epsilon=epsilon,
                      weight_attr=False if not scale else param_attr,
                      bias_attr=False if not shift else bias_attr)
    return layer(input)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", **kw):
    from ..nn.layers.norm import GroupNorm

    _check_nchw({"data_layout": data_layout}, "group_norm")
    layer = GroupNorm(groups, input.shape[1], epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  **kw):
    from ..nn.layers.norm import InstanceNorm2D

    layer = InstanceNorm2D(input.shape[1], epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def prelu(x, mode="all", param_attr=None, **kw):
    """reference: static/nn/common.py prelu (mode: all/channel/element)."""
    from ..nn import functional as F
    from ..tensor import creation

    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1]]
    elif mode == "element":
        shape = list(x.shape[1:])
    else:
        raise ValueError(f"prelu mode must be all/channel/element, "
                         f"got {mode!r}")
    alpha = creation.create_parameter(shape, "float32")
    alpha.set_value(np.full(shape, 0.25, np.float32))
    if mode == "element":
        # per-element alpha broadcasts over batch; F.prelu's channel
        # reshape only fits scalar/per-channel weights
        from ..core.dispatch import apply_op

        def _pe(x, a):
            import jax.numpy as jnp

            return jnp.where(x >= 0, x, a[None] * x)

        return apply_op("prelu_element", _pe, x, alpha)
    return F.prelu(x, alpha)


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            **kw):
    """reference: fluid/layers/nn.py bilinear_tensor_product."""
    from ..nn import functional as F
    from ..tensor import creation

    w = creation.create_parameter([size, x.shape[-1], y.shape[-1]],
                                  "float32")
    b = creation.create_parameter([size], "float32", is_bias=True)
    return F.bilinear(x, y, w, b)


def data_norm(input, epsilon=1e-5, param_attr=None, **kw):
    """reference: fluid/layers/nn.py data_norm — normalize by accumulated
    batch statistics (batch_size/batch_sum/batch_square_sum buffers)."""
    from ..core.dispatch import apply_op
    from ..core.tensor import Tensor

    d = input.shape[-1]
    # statistics are accumulators, NOT trainable weights: plain
    # persistable Tensors stay out of program.params, so the static
    # optimizer can never gradient-update them
    size = Tensor(np.full([d], 1e4, np.float32), stop_gradient=True)
    ssum = Tensor(np.zeros([d], np.float32), stop_gradient=True)
    sqsum = Tensor(np.full([d], 1e4, np.float32), stop_gradient=True)

    def _dn(x, n, s, sq, *, eps):
        import jax.numpy as jnp

        # reference data_norm_op.cc:302: mean = sum/size,
        # scale = sqrt(size / square_sum) — square_sum is pre-seeded so
        # no mean subtraction happens in the op
        del eps
        mean = s / n
        scale = jnp.sqrt(n / sq)
        return (x - mean) * scale

    return apply_op("data_norm", _dn, input, size, ssum, sqsum,
                    eps=float(epsilon))


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: operators/row_conv_op.cc — lookahead convolution over
    the time dim: out[t] = sum_{k=0..K} w[k] * x[t+k]."""
    from ..core.dispatch import apply_op
    from ..tensor import creation

    K = int(future_context_size)
    d = input.shape[-1]
    w = creation.create_parameter([K + 1, d], "float32")

    def _rc(x, w):
        import jax.numpy as jnp

        T = x.shape[-2]
        out = jnp.zeros_like(x)
        for k in range(w.shape[0]):
            seg = x[..., k:T, :] * w[k]
            out = out.at[..., :T - k, :].add(seg)
        return out

    out = apply_op("row_conv", _rc, input, w)
    return _apply_act(out, {"act": act})


def crf_decoding(potentials, transition_params=None, lengths=None,
                 **kw):
    """Viterbi decode of linear-chain CRF unary potentials (reference:
    operators/crf_decoding_op.h; paddle.text.ViterbiDecoder semantics):
    returns the argmax tag path [B, T]. With per-sample ``lengths``,
    steps beyond each length are frozen (stop weights apply at the true
    last step; padded path positions repeat the final tag)."""
    from ..core.dispatch import apply_op

    if transition_params is None:
        raise ValueError("crf_decoding needs transition_params [N+2, N] "
                         "or [N, N]")
    def _viterbi(unary, trans, lens):
        import jax
        import jax.numpy as jnp

        if lens is None:
            # resolve from the TRACED shape: baking the build-time
            # placeholder dims would freeze every step for programs
            # declared with dynamic (-1) batch/seq sizes
            lens = jnp.full((unary.shape[0],), unary.shape[1],
                            dtype=jnp.int32)
        # paddle layout [N+2, N] (crf_decoding_op.h): row 0 = start
        # weights, row 1 = stop weights, rows 2.. = pairwise transitions;
        # a bare [N, N] is pairwise-only
        n = unary.shape[-1]
        if trans.shape[0] == n + 2:
            start, stop, pair = trans[0], trans[1], trans[2:]
        else:
            start = jnp.zeros(n)
            stop = jnp.zeros(n)
            pair = trans[:n, :n]

        B = unary.shape[0]
        ident = jnp.broadcast_to(jnp.arange(n)[None, :], (B, n))

        def step(carry, xs):
            score, t = carry
            emit = xs
            cand = score[:, :, None] + pair[None, :, :]  # [B, from, to]
            best = jnp.max(cand, axis=1) + emit
            back = jnp.argmax(cand, axis=1)
            live = (t < lens)[:, None]
            # frozen samples: score unchanged, backpointer = identity so
            # backtracking walks the final tag through the padding
            return ((jnp.where(live, best, score), t + 1),
                    jnp.where(live, back, ident))

        first = unary[:, 0] + start[None, :]
        (score, _), backs = jax.lax.scan(
            step, (first, jnp.asarray(1)),
            jnp.swapaxes(unary[:, 1:], 0, 1))
        last = jnp.argmax(score + stop[None, :], axis=-1)  # [B]

        def backtrack(carry, back):
            tag = carry
            prev = jnp.take_along_axis(back, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # reverse scan emits the tag at step t+1 into slot t; the final
        # carry is the step-0 tag
        tag0, path = jax.lax.scan(backtrack, last, backs, reverse=True)
        return jnp.concatenate([tag0[:, None],
                                jnp.swapaxes(path, 0, 1)], axis=1)

    return apply_op("crf_decoding", _viterbi, potentials,
                    transition_params, lengths)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    """Deformable conv v1/v2 with in-graph parameter creation
    (reference: python/paddle/static/nn/common.py:168 deform_conv2d,
    operators/deformable_conv_op.cc). mask=None selects v1. The compute
    core lives in vision.ops.deform_conv2d (vectorized bilinear gathers
    + one MXU einsum — no im2col scratch, so im2col_step is moot)."""
    from ..tensor import creation
    from ..vision.ops import deform_conv2d as _dc

    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = x.shape[1]
    w = creation.create_parameter(
        [num_filters, cin // groups, ks[0], ks[1]], "float32",
        attr=weight_attr)
    b = None
    if bias_attr is not False:
        b = creation.create_parameter([num_filters], "float32",
                                      attr=bias_attr, is_bias=True)
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def multi_box_head(*args, **kwargs):
    raise NotImplementedError(
        "multi_box_head: compose prior_box/density_prior_box + conv2d "
        "heads directly (see paddle_tpu.vision.ops)")


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference:
    python/paddle/fluid/layers/loss.py nce + operators/nce_op.h
    NCEKernel): per row, sigmoid logits o for the true labels and
    num_neg_samples sampled noise classes; cost = -log(o/(o+B)) for
    true, -log(B/(o+B)) for noise, with B = q(class) * num_neg.

    TPU notes: negatives are drawn host-side at call time (one set per
    trace — the reference's per-batch CPU sampler moved outside the
    compiled region) and the cost itself is pure jnp, so autodiff trains
    weight/bias without the reference's hand-written NCEGradKernel.
    Returns [B, 1]."""
    from ..core.dispatch import apply_op
    from ..tensor import creation

    dim = input.shape[-1]
    n = int(num_total_classes)
    k = 10 if num_neg_samples is None else int(num_neg_samples)
    num_true = label.shape[1] if len(label.shape) > 1 else 1
    bsz = input.shape[0]
    w = creation.create_parameter([n, dim], "float32", attr=param_attr)
    b = creation.create_parameter([n], "float32", attr=bias_attr,
                                  is_bias=True)
    rng = np.random.RandomState(seed if seed else None)
    if sampler == "uniform":
        negs = rng.randint(0, n, size=(bsz, k))
    elif sampler == "log_uniform":
        # inverse-transform sampling of f(x) ~ 1/((x+1) ln(range+1))
        # (reference math/sampler.cc LogUniformSampler::Sample)
        u = rng.rand(bsz, k)
        negs = (np.exp(u * np.log(n)).astype(np.int64) - 1) % n
    elif sampler == "custom_dist":
        p = np.asarray(custom_dist, np.float64)
        p = p / p.sum()
        negs = rng.choice(n, size=(bsz, k), p=p)
    else:
        raise ValueError(f"sampler must be uniform/log_uniform/"
                         f"custom_dist, got {sampler!r}")
    negs_t = np.asarray(negs, np.int64)
    dist = None if sampler != "custom_dist" else \
        np.asarray(custom_dist, np.float32)

    def _nce(x, lab, negs, sw, dist_arr, w, b, *, n, k, num_true, samp):
        import jax
        import jax.numpy as jnp

        lab = lab.reshape(lab.shape[0], -1)
        sl = jnp.concatenate([lab.astype(jnp.int32),
                              negs.astype(jnp.int32)], axis=1)
        logits = jnp.einsum("bd,bsd->bs", x, w[sl]) + b[sl]
        o = jax.nn.sigmoid(logits)
        if samp == "uniform":
            q = jnp.full(sl.shape, 1.0 / n)
        elif samp == "log_uniform":
            q = jnp.log((sl + 2.0) / (sl + 1.0)) / jnp.log(float(n))
        else:
            # runtime operand, NOT a static kwarg: a vocab-sized tuple
            # in the cache key costs O(V) hashing per call and bakes a
            # million-element constant into the HLO
            q = dist_arr[sl]
        B = q * k
        is_true = jnp.arange(sl.shape[1]) < num_true
        cost = jnp.where(is_true[None, :],
                         -jnp.log(o / (o + B)),
                         -jnp.log(B / (o + B)))
        out = jnp.sum(cost, axis=1, keepdims=True)
        if sw is not None:
            out = out * sw.reshape(-1, 1)
        return out

    from ..core.tensor import Tensor

    dist_t = None if dist is None else Tensor(dist, stop_gradient=True)
    return apply_op("nce", _nce, input, label,
                    Tensor(negs_t, stop_gradient=True), sample_weight,
                    dist_t, w, b, n=n, k=k, num_true=int(num_true),
                    samp=sampler)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Static spectral_norm (reference: fluid/layers/nn.py:3631) —
    instantiates nn.SpectralNorm so the power iteration shares the one
    maintained implementation (persistent u/v ride its buffers)."""
    from ..nn.layers.norm import SpectralNorm

    layer = SpectralNorm(list(weight.shape), dim=dim,
                         power_iters=power_iters, eps=eps)
    return layer(weight)
