"""paddle.static — static-graph facade (reference: python/paddle/static/,
fluid/framework.py Program:4301, executor.py Executor:916).

TPU-native design: a Program is a *recorded op list* (captured by running
user graph-building code eagerly on placeholder arrays through the shared
dispatch layer), and Executor.run replays it as one jitted pure function
of (params, feeds) — XLA is the executor, ParallelExecutor, and memory
planner in one. optimizer.minimize() under static mode attaches a
functional train step (grads via jax.grad over the replay + optimizer
update), the append_backward analog.
"""
from .program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    Executor, data, append_backward, gradients, name_scope, global_scope,
    scope_guard, cpu_places, cuda_places, tpu_places, device_guard,
    save_inference_model, load_inference_model, normalize_program,
)
from .input_spec import InputSpec  # noqa: F401
from .compat import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, ParallelExecutor,
    Print, Variable, accuracy, auc, create_global_var, create_parameter,
    deserialize_persistables, deserialize_program, load, load_from_file,
    load_program_state, py_func, save, save_to_file, serialize_persistables,
    load_vars, save_vars, serialize_program, set_program_state, xpu_places,
)
from .program import _Scope as Scope  # noqa: F401
from .. import amp  # noqa: F401 (paddle.static.amp alias)
from ..framework.param_attr import WeightNormParamAttr  # noqa: F401
from .. import nn as _nn_module


class _StaticNN:
    """paddle.static.nn compat namespace (reference: python/paddle/static/nn):
    fc/conv2d/batch_norm program-building helpers, falling back to the main
    paddle.nn module for everything else."""

    def __getattr__(self, name):
        return getattr(_nn_module, name)

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
        from .. import tensor as pt
        from ..nn import functional as F
        from ..tensor import creation

        in_dim = 1
        for d in x.shape[num_flatten_dims:]:
            in_dim *= d
        # leading (batch) dim stays symbolic: the program is replayed with
        # real feed shapes, so bake -1 rather than the placeholder's dim
        lead = [-1] + list(x.shape[1:num_flatten_dims])
        flat = pt.reshape(x, lead + [in_dim])
        w = creation.create_parameter([in_dim, size], "float32")
        b = creation.create_parameter([size], "float32", is_bias=True)
        out = F.linear(flat, w, b)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def batch_norm(x, **kw):
        from ..nn.layers.norm import BatchNorm

        bn = BatchNorm(x.shape[1])
        return bn(x)

    @staticmethod
    def conv2d(x, num_filters, filter_size, stride=1, padding=0, **kw):
        from ..nn.layers.conv import Conv2D

        conv = Conv2D(x.shape[1], num_filters, filter_size, stride, padding)
        return conv(x)


nn = _StaticNN()

# LayerHelper-style builders (reference: python/paddle/static/nn)
from . import nn_extra as _nn_extra  # noqa: E402

for _name in ("embedding", "sparse_embedding", "conv2d_transpose",
              "conv3d", "conv3d_transpose", "layer_norm", "group_norm",
              "instance_norm", "prelu", "bilinear_tensor_product",
              "data_norm", "row_conv", "crf_decoding", "deform_conv2d",
              "multi_box_head", "nce"):
    setattr(_StaticNN, _name, staticmethod(getattr(_nn_extra, _name)))
# sequence ops ride the ragged module (LoD -> padding+lengths design)
from ..text import ragged as _ragged  # noqa: E402

for _name in ("sequence_softmax", "sequence_reverse", "sequence_pad",
              "sequence_unpad", "sequence_expand", "sequence_concat"):
    if hasattr(_ragged, _name):
        setattr(_StaticNN, _name, staticmethod(getattr(_ragged, _name)))
setattr(_StaticNN, "py_func", staticmethod(py_func))
setattr(_StaticNN, "create_parameter", staticmethod(create_parameter))
setattr(_StaticNN, "spectral_norm", staticmethod(_nn_extra.spectral_norm))

nn_compat = nn  # back-compat alias

from . import nn_control_flow  # noqa: E402
from .nn_control_flow import case, cond, switch_case, while_loop  # noqa: F401,E402

# expose the control-flow layers on the static.nn namespace (reference:
# paddle.static.nn.cond / while_loop / case / switch_case)
for _cf_name, _cf in (("cond", cond), ("while_loop", while_loop),
                      ("case", case), ("switch_case", switch_case)):
    setattr(_StaticNN, _cf_name, staticmethod(_cf))
