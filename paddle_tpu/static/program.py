"""Program / Executor (reference: fluid/framework.py Program:4301,
executor.py Executor:916 -> C++ executor.cc:166; backward.py
append_backward).

A Program records (fn, kwargs, input-refs, output-refs) tuples captured
from the dispatch layer while user graph-building code runs on
placeholder arrays. Executor.run replays the list as a pure jitted
function keyed by feed shapes.
"""
import contextlib
import contextvars

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor, Parameter

_RECORDER = contextvars.ContextVar("program_recorder", default=None)


class _OpRecord:
    __slots__ = ("fn", "kwargs", "in_refs", "out_ids")

    def __init__(self, fn, kwargs, in_refs, out_ids):
        self.fn = fn
        self.kwargs = kwargs
        self.in_refs = in_refs  # list of ("var", id) | ("const", value)
        self.out_ids = out_ids


class Program:
    def __init__(self):
        self.ops = []
        self.placeholders = {}  # name -> Tensor(dummy)
        self.params = {}  # id -> Parameter
        self.var_names = {}  # id -> name (fetch support)
        self.keep = []  # keep recorded tensors alive (ids stable)
        self.id2tensor = {}
        self.train_attach = None  # (optimizer, loss_tensor)
        self.random_seed = 0
        self._is_start_up = False

    # -- recording callbacks (from dispatch hook) --
    def record(self, fn, kwargs, args, outs):
        in_refs = []
        for a in args:
            if isinstance(a, Tensor):
                in_refs.append(("var", id(a)))
                self.keep.append(a)
                self.id2tensor[id(a)] = a
                if isinstance(a, Parameter) or (not a.stop_gradient and a._node is None
                                                and a.persistable):
                    self.params[id(a)] = a
            else:
                in_refs.append(("const", a))
        out_ids = []
        for o in outs:
            out_ids.append(id(o))
            self.keep.append(o)
            self.id2tensor[id(o)] = o
        self.ops.append(_OpRecord(fn, kwargs, in_refs, out_ids))

    # -- program API compat --
    def global_block(self):
        return self

    def all_parameters(self):
        return list(self.params.values())

    def list_vars(self):
        return list(self.placeholders.values())

    def clone(self, for_test=False):
        """for_test=True strips the optimizer attachment (reference:
        framework.py Program.clone pruning backward+optimize ops), so
        Executor.run on the clone is inference-only. Ops/params/
        placeholders are shared with the original — static programs are
        immutable after recording."""
        if not for_test:
            return self
        import copy

        test = copy.copy(self)
        test.train_attach = None
        return test

    def var(self, name):
        if name in self.placeholders:
            return self.placeholders[name]
        # recorded (computed) variables: resolve by Tensor.name
        for vid, vname in self.var_names.items():
            if vname == name:
                return self.id2tensor.get(vid)
        for t in self.id2tensor.values():
            if getattr(t, "name", None) == name:
                return t
        raise KeyError(
            f"no variable named {name!r} in program (placeholders: "
            f"{sorted(self.placeholders)}; recorded tensors are fetchable "
            f"by object, or by name when Tensor.name is set)")

    # -- replay --
    def _replay(self, param_arrays, feed_arrays, placeholder_ids, param_ids):
        env = {}
        for pid, arr in zip(placeholder_ids, feed_arrays):
            env[pid] = arr
        for pid, arr in zip(param_ids, param_arrays):
            env[pid] = arr
        for op in self.ops:
            ins = []
            for kind, v in op.in_refs:
                if kind == "var":
                    if v in env:
                        ins.append(env[v])
                    else:
                        t = self.id2tensor.get(v)
                        ins.append(None if t is None else t._value)
                else:
                    ins.append(v)
            outs = op.fn(*ins, **op.kwargs)
            if not isinstance(outs, (list, tuple)):
                outs = (outs,)
            for oid, o in zip(op.out_ids, outs):
                env[oid] = o
        return env


class Executor:
    """reference: executor.py:916. Compiles the recorded program with
    jax.jit per (feed-spec, fetch-set); the XLA executable is the
    ParallelExecutor analog (sharded feeds parallelize over the mesh)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        program = program or default_main_program()
        if hasattr(program, "_program"):  # CompiledProgram wrapper
            program = program._program
        feed = feed or {}
        fetch_list = fetch_list or []
        if program._is_start_up or not program.ops:
            return []

        feed_names = sorted(feed.keys())
        placeholder_ids = [id(program.placeholders[n]) for n in feed_names
                           if n in program.placeholders]
        feed_arrays = [jnp.asarray(np.asarray(feed[n])) for n in feed_names
                       if n in program.placeholders]
        param_items = sorted(program.params.items())
        param_ids = [pid for pid, _ in param_items]
        param_tensors = [p for _, p in param_items]
        fetch_ids = tuple(id(f) if isinstance(f, Tensor) else f for f in fetch_list)
        spec = tuple((a.shape, str(a.dtype)) for a in feed_arrays)
        cache_key = (id(program), tuple(feed_names), fetch_ids, spec,
                     program.train_attach is not None, len(program.ops))

        compiled = self._cache.get(cache_key)
        if compiled is None:
            compiled = self._compile(program, placeholder_ids, param_ids, fetch_ids)
            self._cache[cache_key] = compiled

        param_arrays = [p._value for p in param_tensors]
        if program.train_attach is not None:
            opt = program.train_attach[0]
            opt_state = getattr(program, "_opt_state", None)
            if opt_state is None:
                opt_state = [opt._init_state(a) for a in param_arrays]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            fetches, new_params, new_state = compiled(param_arrays, feed_arrays,
                                                      opt_state, lr)
            for p, a in zip(param_tensors, new_params):
                p._value = a
            program._opt_state = new_state
            if opt._lr_scheduler is not None:
                pass  # user steps the scheduler explicitly
        else:
            fetches = compiled(param_arrays, feed_arrays)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def _compile(self, program, placeholder_ids, param_ids, fetch_ids):
        train = program.train_attach is not None
        if not train:
            def infer_fn(param_arrays, feed_arrays):
                with dispatch.trace_mode():
                    env = program._replay(param_arrays, feed_arrays,
                                          placeholder_ids, param_ids)
                return tuple(env[fid] for fid in fetch_ids)

            return jax.jit(infer_fn)

        opt, loss_t = program.train_attach
        loss_id = id(loss_t)

        def train_fn(param_arrays, feed_arrays, opt_state, lr):
            def loss_of(params):
                with dispatch.trace_mode():
                    env = program._replay(params, feed_arrays, placeholder_ids,
                                          param_ids)
                return env[loss_id].sum(), env

            (loss_val, env), grads = jax.value_and_grad(loss_of, has_aux=True)(
                list(param_arrays))
            if opt._grad_clip is not None:
                grads = opt._grad_clip.clip_arrays(grads)
            hypers = opt._hypers()
            l1_coeff = type(opt)._take_l1(hypers)
            new_params, new_state = [], []
            for p, g, st in zip(param_arrays, grads, opt_state):
                g = g.astype(p.dtype)
                if l1_coeff:
                    g = g + l1_coeff * jnp.sign(p)
                out = type(opt)._update(p, g, lr, *st, **hypers)
                # static unroll: one update per parameter, bounded by the
                # program's parameter count (not by traced data)
                new_params.append(out[0])      # tracelint: disable=TPU007
                new_state.append(tuple(out[1:]))  # tracelint: disable=TPU007
            fetches = tuple(env[fid] for fid in fetch_ids)
            return fetches, new_params, new_state

        return jax.jit(train_fn)

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=1, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Dataset-channel training loop (reference: executor.py
        train_from_dataset -> MultiTrainer + HogwildWorker, trainer.h:52).
        Dataset batches (labels, {slot: ids}) feed the program's
        placeholders by slot name plus 'label'. ``thread`` workers
        overlap dataset decode/feed conversion; the optimization steps
        themselves serialize on the program lock (Program replay swaps
        parameter state non-atomically — unlike the reference's C++
        scopes, concurrent replay is not safe, so this trades the
        reference's lock-free hogwild updates for pipeline overlap
        only). Returns the per-batch fetch results in completion
        order."""
        import threading as _threading

        from ..distributed.fleet.trainer import MultiTrainer

        program = program or default_main_program()
        fetch_list = fetch_list or []
        results = []
        lock = _threading.Lock()

        def train_one(labels, slots):
            feed = dict(slots)
            feed["label"] = np.asarray(labels, np.float32).reshape(-1, 1)
            with lock:  # program replay mutates params; hogwild applies
                out = self.run(program, feed=feed, fetch_list=fetch_list)
            results.append(out)
            return float(np.asarray(out[0]).ravel()[0]) if out else 0.0

        MultiTrainer(train_one,
                     num_threads=max(1, int(thread))).train_from_dataset(
            dataset)
        return results

    def close(self):
        pass


_default_main = Program()
_default_startup = Program()
_default_startup._is_start_up = True


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    old_main, old_startup = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    token = _RECORDER.set(main_program)
    dispatch.PROGRAM_HOOK = main_program
    try:
        yield
    finally:
        _RECORDER.reset(token)
        dispatch.PROGRAM_HOOK = old_main if _recording_active(old_main) else None
        _default_main = old_main
        _default_startup = old_startup


def _recording_active(prog):
    return False


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data — placeholder (reference: static/input.py data)."""
    import numpy as np

    shape = [1 if (s is None or s == -1) else int(s) for s in shape]
    dummy = Tensor(np.zeros(shape, np.dtype(dtype) if dtype != "bfloat16" else np.float32))
    dummy.name = name
    prog = _RECORDER.get() or default_main_program()
    prog.placeholders[name] = dummy
    prog.keep.append(dummy)
    return dummy


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None,
                    checkpoints=None):
    """Marks loss for the functional grad pass (reference: backward.py:1009)."""
    prog = _RECORDER.get() or default_main_program()
    prog._backward_loss = loss
    return []


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core import tape

    return tape.grad(targets, inputs, target_gradients, allow_unused=True)


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


class _Scope:
    def __init__(self):
        self.vars = {}

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    yield


def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    return [CPUPlace()]


def cuda_places(device_ids=None):
    from ..core.place import CUDAPlace

    ids = device_ids or range(1)
    return [CUDAPlace(i) for i in ids]


def tpu_places(device_ids=None):
    from ..core.place import TPUPlace
    import jax as _jax

    if device_ids is None:
        device_ids = range(len(_jax.devices()))
    return [TPUPlace(i) for i in device_ids]


@contextlib.contextmanager
def device_guard(device=None):
    yield


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, program=None):
    """reference: static/io.py save_inference_model (prune program to
    feed/fetch + save persistables via save ops). TPU-native: export the
    recorded program's replay closed over feeds/fetches as StableHLO in the
    jit.save format, so ``load_inference_model`` / ``inference.Predictor``
    can serve it."""
    from ..jit.save_load import write_artifacts

    program = program or default_main_program()
    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    placeholder_ids = [id(v) for v in feed_vars]
    param_items = sorted(program.params.items())
    param_ids = [pid for pid, _ in param_items]
    param_tensors = [p for _, p in param_items]
    fetch_ids = [id(f) for f in fetch_vars]

    def infer_fn(param_list, buffer_list, *feeds):
        del buffer_list  # static programs carry no buffers
        with dispatch.trace_mode():
            env = program._replay(list(param_list), list(feeds),
                                  placeholder_ids, param_ids)
        return tuple(env[fid] for fid in fetch_ids)

    param_names = []
    used_names = set()
    for i, p in enumerate(param_tensors):
        name = getattr(p, "name", None) or f"param_{i}"
        # duplicate names would collapse in the saved params dict and
        # silently drop weights; uniquify deterministically
        if name in used_names:
            k = 1
            while f"{name}__dup{k}" in used_names:
                k += 1
            name = f"{name}__dup{k}"
        used_names.add(name)
        param_names.append(name)
    param_arrays = [p._value for p in param_tensors]
    param_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in param_arrays]
    feed_specs = [jax.ShapeDtypeStruct(v._value.shape, v._value.dtype)
                  for v in feed_vars]
    write_artifacts(path_prefix, jax.jit(infer_fn), (param_specs, []), feed_specs,
                    {n: np.asarray(a) for n, a in zip(param_names, param_arrays)},
                    {})


def load_inference_model(path_prefix, executor):
    """Returns [program(callable layer), feed_target_names, fetch_targets]
    (reference: static/io.py load_inference_model)."""
    from ..jit import load as jit_load

    layer = jit_load(path_prefix)
    feed_names = [f"x{i}" for i in range(len(layer._input_specs))]
    return [layer, feed_names, []]


def normalize_program(program, feed_vars, fetch_vars):
    return program
