"""InputSpec (reference: python/paddle/static/input.py)."""
import numpy as np


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = "float32" if dtype is None else (
            dtype if isinstance(dtype, str) else np.dtype(dtype).name)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(np.dtype(tensor.dtype)), name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec([batch_size] + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
