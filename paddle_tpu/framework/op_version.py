"""Op version registry (reference:
paddle/fluid/framework/op_version_registry.h:395 REGISTER_OP_VERSION +
op_compatible_info.cc): per-op semantic version + change notes, saved
into model artifacts and checked on load so old programs fail loudly
(not silently wrong) when an op's semantics moved."""
import warnings

_REGISTRY = {}


class OpVersion:
    def __init__(self, op, version=1):
        self.op = op
        self.version = version
        self.changes = []  # list of (version, note)

    def mod(self, note):
        """Record a semantic change, bumping the version
        (REGISTER_OP_VERSION(...).AddCheckpoint analog)."""
        self.version += 1
        self.changes.append((self.version, note))
        return self


def register_op_version(op, note=None):
    """Register (or bump, when note is given) an op's version."""
    entry = _REGISTRY.setdefault(op, OpVersion(op))
    if note is not None:
        entry.mod(note)
    return entry


def get_op_version(op):
    entry = _REGISTRY.get(op)
    return entry.version if entry else 1


def all_op_versions():
    return {op: e.version for op, e in _REGISTRY.items()}


def check_compat(saved_versions, where="model"):
    """Loaded-artifact check (op_compatible_info.cc analog): warn when
    the saved program used a different op version than the runtime."""
    mismatches = {}
    for op, v in (saved_versions or {}).items():
        cur = get_op_version(op)
        if cur != v:
            mismatches[op] = (v, cur)
    if mismatches:
        warnings.warn(
            f"op version mismatch loading {where}: "
            + ", ".join(f"{op} saved v{sv} vs runtime v{cv}"
                        for op, (sv, cv) in mismatches.items()),
            RuntimeWarning, stacklevel=2)
    return mismatches


# seed versions for ops whose semantics changed across this framework's
# rounds (the registry is additive; plain v1 ops need no entry)
register_op_version("batch_norm_train",
                    "running stats update under traced training (r3)")
register_op_version("take_along_axis")
