"""paddle.framework — save/load + misc (reference:
python/paddle/framework/io.py:492 save, :663 load)."""
import os
import pickle

import numpy as np

from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from ..core.tensor import Tensor
from ..core import place as place_mod


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value),
                "name": obj.name, "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name")
            return t
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save — pickle of (nested) state dicts with Tensors as numpy.

    Atomic: the pickle lands in a same-directory temp file that is
    fsynced and os.replace'd into place, so a crash mid-save leaves the
    previous checkpoint intact instead of a truncated pickle."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d or ".",
                       f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_to_saveable(obj), f, protocol=protocol)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load(path, **configs):
    """paddle.load — inverse of save."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy=configs.get("return_numpy", False))


def get_default_dtype():
    from ..core.dtype import get_default_dtype as g

    return g()


def set_default_dtype(d):
    from ..core.dtype import set_default_dtype as s

    return s(d)


# compat names
CPUPlace = place_mod.CPUPlace
CUDAPlace = place_mod.CUDAPlace
TPUPlace = place_mod.TPUPlace


def in_dygraph_mode():
    from ..jit import in_dynamic_mode

    return in_dynamic_mode()
