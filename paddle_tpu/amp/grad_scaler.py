"""GradScaler (reference: python/paddle/amp/grad_scaler.py:20 →
dygraph/amp/loss_scaler.py:27 AmpScaler; kernels operators/amp/
check_finite_and_unscale_op.cc, update_loss_scaling_op.cc).

bfloat16 (TPU default) does not need loss scaling — the scaler becomes a
transparent pass-through unless fp16 is in use, but keeps the dynamic
loss-scaling state machine for API and fp16 parity.
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._bad_step_monitor = None

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._params
        inv = 1.0 / self._scale
        found_inf = False
        for p in params:
            if p._grad is None:
                continue
            g = p._grad * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found_inf = True
            p._grad = g
        self._found_inf = found_inf

    minimize_unscale = unscale_

    def attach_bad_step_monitor(self, monitor):
        """Feed this scaler's overflow skips into a
        resilience.BadStepMonitor: the scaler keeps doing its dynamic
        re-scaling, and after the monitor's threshold of CONSECUTIVE
        skipped steps it triggers the checkpoint-rollback policy (the
        two defenses compose instead of double-counting — see
        MIGRATION.md)."""
        self._bad_step_monitor = monitor
        return monitor

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            if self._bad_step_monitor is not None:
                self._bad_step_monitor.record(False)
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        if self._bad_step_monitor is not None:
            self._bad_step_monitor.record(self._found_inf)
        self.update()

    def minimize(self, optimizer, loss, **kwargs):
        loss.backward()
        self.step(optimizer)
        return [], []

    def update(self):
        if not (self._enable and self._use_dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def get_loss_scaling(self):
        return Tensor(np.asarray([self._scale], np.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d["scale"]
        self._good_steps = d["good_steps"]
        self._bad_steps = d["bad_steps"]


class GradScaler(AmpScaler):
    pass
