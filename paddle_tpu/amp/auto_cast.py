"""auto_cast context (reference: python/paddle/amp/auto_cast.py,
imperative/amp_auto_cast.cc AmpOperators white/black lists)."""
import contextlib
import contextvars

import numpy as np
import jax.numpy as jnp

# bf16/fp16-safe ops (MXU-bound) — cast inputs down.
AMP_WHITE_LIST = {
    "matmul", "bmm", "mm", "linear", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "conv1d_transpose", "einsum", "fused_lstm", "fused_gru",
    "fused_rnn", "sdpa", "flash_attention", "addmm",
}

# numerically-sensitive ops — force fp32.
AMP_BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "bce", "bce_logits",
    "kl_div", "mse_loss", "l1_loss", "smooth_l1_loss", "sum", "mean", "logsumexp",
    "cumsum", "layer_norm", "batch_norm_train", "batch_norm_infer", "group_norm",
    "instance_norm", "p_norm", "softmax_with_cross_entropy", "sigmoid_focal_loss",
}

white_list = AMP_WHITE_LIST
black_list = AMP_BLACK_LIST

_AMP_STATE = contextvars.ContextVar("amp_state", default=None)


class _AmpState:
    def __init__(self, enable, dtype, level, custom_white, custom_black):
        self.enable = enable
        self.dtype = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
        self.level = level
        self.white = (AMP_WHITE_LIST | set(custom_white or ())) - set(custom_black or ())
        self.black = (AMP_BLACK_LIST | set(custom_black or ())) - set(custom_white or ())


def _is_float_arr(v):
    try:
        d = np.dtype(v.dtype)
    except Exception:
        return False
    return d.kind == "f" or str(v.dtype) == "bfloat16"


def amp_cast_hook(name, arrays):
    """Called from core.dispatch.apply_op for every op."""
    state = _AMP_STATE.get()
    if state is None or not state.enable:
        return arrays
    if name in state.white:
        tgt = state.dtype
    elif name in state.black:
        tgt = jnp.float32
    elif state.level == "O2":
        tgt = state.dtype
    else:
        return arrays
    out = []
    for v in arrays:
        if v is not None and _is_float_arr(v) and v.dtype != tgt:
            out.append(v.astype(tgt))
        else:
            out.append(v)
    return out


def suspend_auto_cast():
    """Disable the per-op AMP hook for a region (the pipeline trunk
    uses explicit per-stage casts instead: per-op converts inside the
    manual shard_map region trip an XLA-CPU legalization CHECK).
    Exactly ``auto_cast(enable=False)`` — one hook-off protocol."""
    return auto_cast(enable=False)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    state = _AmpState(enable, dtype, level, custom_white_list, custom_black_list)
    token = _AMP_STATE.set(state)
    try:
        yield
    finally:
        _AMP_STATE.reset(token)


amp_guard = auto_cast


def _install():
    from ..core import dispatch

    dispatch.AMP_HOOK = amp_cast_hook


_install()
