"""paddle.amp — automatic mixed precision (reference: python/paddle/amp/
auto_cast.py:20, grad_scaler.py:20; C++ trace-time cast
imperative/amp_auto_cast.cc:27-47; op lists fluid/contrib/mixed_precision/
fp16_lists.py).

On TPU the native reduced precision is bfloat16 (MXU-preferred), so
level='O1' defaults to bf16 and loss scaling is a no-op unless fp16 is
requested explicitly. The cast hook lives in core.dispatch so eager and
traced modes share the same per-op policy — the amp_auto_cast.cc analog.
"""
from .auto_cast import (  # noqa: F401
    auto_cast, amp_guard, white_list, black_list, AMP_WHITE_LIST, AMP_BLACK_LIST,
)
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Dygraph decorate (reference: amp/auto_cast.py decorate). For O2 we
    cast the model parameters to the amp dtype."""
    if level == "O2":
        models_ = models if isinstance(models, (list, tuple)) else [models]
        for m in models_:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers
