"""PyLayer — user-defined forward/backward (reference:
python/paddle/autograd/py_layer.py:21 PyLayerContext, :133 PyLayer).

Implemented over the tape: the custom backward is invoked by a synthetic
tape node whose "op function" defers to the user's static backward.
"""
from ..core import dispatch, tape
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self.container = None
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self.container = tensors

    def saved_tensor(self):
        return self.container

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        pass

    def set_materialize_grads(self, value):
        self._materialize_grads = bool(value)


class _PyLayerNode(tape.Node):
    """Tape node whose backward calls the user's static backward()."""

    __slots__ = ("cls", "ctx", "n_inputs")

    def __init__(self, cls, ctx, in_tensors):
        super().__init__(f"pylayer_{cls.__name__}", None, {}, (), tuple(
            range(len(in_tensors))), in_tensors)
        self.cls = cls
        self.ctx = ctx

    def run_backward(self, cts_by_outidx):
        cts = []
        for i, (shape, dt) in enumerate(self.out_avals):
            ct = cts_by_outidx.get(i)
            if ct is None:
                import jax.numpy as jnp

                ct = jnp.zeros(shape, dt)
            cts.append(Tensor(ct, stop_gradient=True))
        with dispatch.no_grad_ctx():
            grads = self.cls.backward(self.ctx, *cts)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        out = []
        for g in grads:
            out.append(g._value if isinstance(g, Tensor) else g)
        return tuple(out)

    def run_backward_recorded(self, cts_by_outidx):
        """create_graph path: run the user backward with the tape ON and
        Tensor cotangents, so grad-of-grad records through it."""
        import jax.numpy as jnp

        cts = []
        for i, (shape, dt) in enumerate(self.out_avals):
            ct = cts_by_outidx.get(i)
            if ct is None:
                ct = Tensor(jnp.zeros(shape, dt), stop_gradient=True)
            cts.append(ct)
        grads = self.cls.backward(self.ctx, *cts)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        return tuple(g if isinstance(g, Tensor) or g is None
                     else Tensor(jnp.asarray(g), stop_gradient=True)
                     for g in grads)


# teach the tape engine about PyLayer nodes
_orig_run_node_backward = tape._run_node_backward


def _run_node_backward(node, cts_by_outidx):
    if isinstance(node, _PyLayerNode):
        return node.run_backward(cts_by_outidx)
    return _orig_run_node_backward(node, cts_by_outidx)


tape._run_node_backward = _run_node_backward


class PyLayer:
    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        requires_grad = dispatch.tape_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        with dispatch.no_grad_ctx():
            outputs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        outs = [o if isinstance(o, Tensor) else Tensor(o) for o in outs]
        if requires_grad:
            node = _PyLayerNode(cls, ctx, tensor_inputs)
            for i, o in enumerate(outs):
                o.stop_gradient = False
                o._node = node
                o._out_idx = i
            node.set_outputs(outs, multi=multi)
        return tuple(outs) if multi else outs[0]

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError
