"""paddle.autograd.backward_mode (reference:
python/paddle/autograd/backward_mode.py) — the reverse-mode entry point
re-exported as its own submodule."""
from ..core.tape import backward  # noqa: F401

__all__ = ["backward"]
