"""paddle.autograd (reference: python/paddle/autograd/: PyLayer py_layer.py:21,
backward; C++ imperative/py_layer_fwd.h)."""
from ..core.tape import backward, grad  # noqa: F401
from ..core.dispatch import no_grad_ctx as no_grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from . import backward_mode  # noqa: F401
