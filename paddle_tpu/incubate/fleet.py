"""Legacy fleet v1 compat facade (reference:
python/paddle/fluid/incubate/fleet/ — the pre-2.0 fleet API that old
user scripts still import: fleet.init(role), fleet.distributed_optimizer,
init_server/init_worker/stop_worker, is_first_worker, worker_index...).

Everything delegates to the modern stack (distributed.fleet +
distributed.ps); the old program-rewrite backends (DistributeTranspiler
program surgery, pslib) have no TPU analog — their capability lives in
the XLA SPMD step and the native PS tables instead.
"""
from ..distributed import fleet as _fleet_mod
from ..distributed.fleet import DistributedStrategy  # noqa: F401
from ..distributed.fleet import Role, UserDefinedRoleMaker  # noqa: F401

_inner = None


def _get():
    global _inner
    if _inner is None:
        _inner = _fleet_mod.Fleet()
    return _inner


def init(role_maker=None, is_collective=False, strategy=None):
    return _get().init(role_maker=role_maker, is_collective=is_collective,
                       strategy=strategy)


def is_first_worker():
    f = _get()
    return f.worker_index() == 0


def worker_index():
    return _get().worker_index()


def worker_num():
    return _get().worker_num()


def is_worker():
    return _get().is_worker()


def is_server():
    return _get().is_server()


def init_server(*args, **kwargs):
    return _get().init_server(*args, **kwargs)


def run_server(*args, **kwargs):
    return _get().run_server(*args, **kwargs)


def init_worker(*args, **kwargs):
    return _get().init_worker(*args, **kwargs)


def stop_worker():
    return _get().stop_worker()


def stop_server():
    return _get().stop_server()


def set_ps_tables(cfgs):
    return _get().set_ps_tables(cfgs)


def distributed_optimizer(optimizer, strategy=None):
    return _get().distributed_optimizer(optimizer, strategy=strategy)


class DistributeTranspiler:
    """reference: fluid/transpiler/distribute_transpiler.py — rewrote
    programs into trainer/pserver halves around send/recv ops. The TPU
    framework has no program surgery: collective training is the SPMD
    step and PS training is the distributed.ps client/server pair, so
    transpile() is a loud pointer, not a silent no-op."""

    def __init__(self, config=None):
        self.config = config

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None,
                  current_endpoint=""):
        raise NotImplementedError(
            "program transpilation does not exist on TPU: use "
            "distributed.spmd.build_train_step for collective training, "
            "or distributed.fleet init_server()/init_worker() (tables in "
            "distributed.ps) for parameter-server training")
