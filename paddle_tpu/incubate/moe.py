"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

The reference (~v2.1) predates its MoE work, so this is green-field
TPU-native design (like ring attention): expert FFN weights are stacked
[E, ...] and SHARDED over 'ep'; routing uses the einsum/dense-dispatch
formulation — every expert's FFN runs for every token and the top-k
gate mask zeroes the rest, with the expert-dim contraction compiling to
a psum over the ep axis. No all_to_all, no capacity overflow, static
shapes end to end: on TPU this trades E/k extra FLOPs (cheap on the
MXU) for zero dynamic dispatch, the standard XLA-friendly MoE shape for
modest expert counts. Sparse a2a dispatch can later ride
collective.alltoall_single without changing this API.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..core.dispatch import apply_op


class MoELayer(nn.Layer):
    """Top-k gated expert FFN block (pre-norm residual not included).

    forward: [B, S, H] -> [B, S, H]. Gate scores are softmaxed over the
    selected top_k experts (renormalized, Switch/GShard style); the
    auxiliary load-balancing loss (GShard aux) is routed through
    ``nn.aux_loss.emit_aux_loss``: in eager mode it lands on
    ``self.aux_loss`` (add it to the objective yourself); inside
    ``spmd.build_train_step`` / ``comm_opt`` train steps it is collected
    into the compiled loss automatically; in inference traces
    (jit.save / onnx.export / generation) it is dropped so no tracer
    escapes onto the layer. Pipeline/FSDP per-stage applies currently
    drop it too — add the aux term explicitly there if it matters.
    """

    def __init__(self, hidden_size, ffn_hidden, num_experts, top_k=2,
                 shard_axis="ep", aux_weight=0.01):
        super().__init__()
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        self.aux_weight = float(aux_weight)
        self.gate = nn.Linear(hidden_size, num_experts)
        k = 1.0 / np.sqrt(hidden_size)
        self.w_up = self.create_parameter(
            [num_experts, hidden_size, ffn_hidden],
            default_initializer=nn.initializer.Uniform(-k, k))
        k2 = 1.0 / np.sqrt(ffn_hidden)
        self.w_down = self.create_parameter(
            [num_experts, ffn_hidden, hidden_size],
            default_initializer=nn.initializer.Uniform(-k2, k2))
        # experts live sharded over 'ep' (spmd.build_train_step honors
        # mp_spec); the contraction over the expert dim emits the psum
        self.w_up.mp_spec = P(shard_axis)
        self.w_down.mp_spec = P(shard_axis)
        self.aux_loss = None

    def forward(self, x):
        logits = self.gate(x)  # [B, S, E]

        def _moe(x, logits, w_up, w_down, *, top_k):
            e = logits.shape[-1]
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            # exact top-k mask from indices (a >=threshold compare would
            # select every tied expert, e.g. all of them on the uniform
            # probs a zero/padding token produces)
            idx = jax.lax.top_k(probs, top_k)[1]            # [B, S, k]
            mask = jnp.sum(jax.nn.one_hot(idx, e, dtype=probs.dtype),
                           axis=-2)
            mask = jnp.minimum(mask, 1.0)
            gates = probs * mask
            gates = gates / jnp.maximum(
                jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
            # dense dispatch: every expert on every token, gated sum.
            # w_up/w_down sharded on e -> per-shard partial experts; the
            # final contraction over e all-reduces over 'ep'.
            h = jnp.einsum("bsh,ehf->besf", x, w_up)
            h = jax.nn.gelu(h)
            y = jnp.einsum("besf,efh->besh", h, w_down)
            out = jnp.einsum("bse,besh->bsh", gates.astype(y.dtype), y)
            # GShard aux loss: E * sum_e (frac tokens routed to e *
            # mean gate prob of e)
            frac = jnp.mean(mask, axis=(0, 1))
            imp = jnp.mean(probs, axis=(0, 1))
            aux = e * jnp.sum(frac / top_k * imp)
            return out, aux.astype(x.dtype)

        out, aux = apply_op("moe_ffn", _moe, x, logits, self.w_up,
                            self.w_down, top_k=self.top_k)
        from ..nn.aux_loss import emit_aux_loss

        emit_aux_loss(self, aux * self.aux_weight)
        return out
