"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

The reference (~v2.1) predates its MoE work, so this is green-field
TPU-native design (like ring attention). Expert FFN weights are stacked
[E, ...] and SHARDED over 'ep'. Two dispatch modes behind one API:

- ``dense``: every expert's FFN runs for every token and the top-k gate
  mask zeroes the rest; the expert-dim contraction compiles to a psum
  over the ep axis. No capacity overflow, static shapes, but E/k wasted
  FLOPs — right only for small expert counts.
- ``capacity`` (GShard/Switch): each expert processes at most
  C = ceil(capacity_factor * k * N / E) tokens; tokens claim capacity
  slots in order (per-expert cumsum) and overflow tokens DROP that
  expert's contribution, exactly the GShard top-2 formulation. Dispatch
  and combine are one-hot einsums — static shapes end to end — so the
  FFN compute is E*C = k*capacity_factor*N token-slots instead of
  E*N: the compute-sparse path. The [E, C, H] expert buffers inherit
  the 'ep' sharding from the weights, so XLA materialises the
  token->expert shuffle as collectives over ep (the all_to_all of the
  GShard paper) while the FFN einsums stay local per expert shard.

- ``alltoall``: the literal GShard layout under ``jax.shard_map`` —
  tokens batch-sharded over the data axes x ep (GShard's groups), each
  shard routes its LOCAL tokens into [E, C, H] capacity buffers, ``lax.all_to_all`` swaps the
  expert dim across shards (each shard then holds its own E/ep experts'
  tokens from every shard), the FFN runs on local expert weights only,
  and a second all_to_all routes results back. Guaranteed all-to-all on
  ICI + per-shard compute exactly E*C/ep token-slots, independent of
  the XLA partitioner's einsum strategy.

``dispatch_mode='auto'`` (default) picks capacity for E >= 8, dense
below — at tiny E dense dispatch wastes little and never drops.
'alltoall' is explicit: it requires a live global mesh with ep > 1,
batch divisible by ep, and E divisible by ep.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..core.dispatch import apply_op
from ..core.jax_compat import shard_map


def _capacity_combine(xf, probs, top_k, cap):
    """GShard combine/dispatch build for one token group (fig. 6 of the
    paper): tokens claim per-expert capacity slots in order, overflow
    drops. Returns (combine [N,E,C] f32, dispatch [N,E,C], top1 idx)."""
    n, e = probs.shape
    topv, topi = jax.lax.top_k(probs, top_k)           # [N, k]
    gates = topv / jnp.maximum(jnp.sum(topv, axis=-1, keepdims=True), 1e-9)
    combine = jnp.zeros((n, e, cap), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)              # slots claimed
    for j in range(top_k):
        mask_j = jax.nn.one_hot(topi[:, j], e)         # [N, E]
        # 0-indexed slot: exclusive cumsum over tokens + slots taken by
        # earlier choices (choice 0 claims before choice 1, like GShard)
        pos_in_e = jnp.cumsum(mask_j, axis=0) - mask_j + counts
        counts = counts + jnp.sum(mask_j, axis=0)
        slot = jnp.sum(pos_in_e * mask_j, axis=-1)     # [N]
        keep = (slot < cap).astype(jnp.float32)
        combine = combine + (
            gates[:, j, None, None] * keep[:, None, None]
            * mask_j[:, :, None]
            * jax.nn.one_hot(slot, cap)[:, None, :])
    dispatch = (combine > 0).astype(xf.dtype)
    return combine, dispatch, topi


def _gshard_aux(probs, topi):
    """GShard aux loss from the full softmax + top-1 routing fraction."""
    e = probs.shape[-1]
    frac = jnp.mean(jax.nn.one_hot(topi[:, 0], e), axis=0)
    imp = jnp.mean(probs, axis=0)
    return e * jnp.sum(frac * imp)


class MoELayer(nn.Layer):
    """Top-k gated expert FFN block (pre-norm residual not included).

    forward: [B, S, H] -> [B, S, H]. Gate scores are softmaxed over the
    selected top_k experts (renormalized, Switch/GShard style); the
    auxiliary load-balancing loss (GShard aux) is routed through
    ``nn.aux_loss.emit_aux_loss``: in eager mode it lands on
    ``self.aux_loss`` (add it to the objective yourself); inside
    ``spmd.build_train_step`` / ``comm_opt`` train steps it is collected
    into the compiled loss automatically; in inference traces
    (jit.save / onnx.export / generation) it is dropped so no tracer
    escapes onto the layer. Pipeline/FSDP per-stage applies currently
    drop it too — add the aux term explicitly there if it matters.
    """

    def __init__(self, hidden_size, ffn_hidden, num_experts, top_k=2,
                 shard_axis="ep", aux_weight=0.01, dispatch_mode="auto",
                 capacity_factor=1.25):
        super().__init__()
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        self.aux_weight = float(aux_weight)
        if dispatch_mode == "auto":
            dispatch_mode = "capacity" if self.num_experts >= 8 else "dense"
        if dispatch_mode not in ("dense", "capacity", "alltoall"):
            raise ValueError(f"dispatch_mode must be 'auto'/'dense'/"
                             f"'capacity'/'alltoall', got {dispatch_mode!r}")
        self.shard_axis = shard_axis
        self.dispatch_mode = dispatch_mode
        self.capacity_factor = float(capacity_factor)
        self.gate = nn.Linear(hidden_size, num_experts)
        k = 1.0 / np.sqrt(hidden_size)
        self.w_up = self.create_parameter(
            [num_experts, hidden_size, ffn_hidden],
            default_initializer=nn.initializer.Uniform(-k, k))
        k2 = 1.0 / np.sqrt(ffn_hidden)
        self.w_down = self.create_parameter(
            [num_experts, ffn_hidden, hidden_size],
            default_initializer=nn.initializer.Uniform(-k2, k2))
        # experts live sharded over 'ep' (spmd.build_train_step honors
        # mp_spec); the contraction over the expert dim emits the psum
        self.w_up.mp_spec = P(shard_axis)
        self.w_down.mp_spec = P(shard_axis)
        self.aux_loss = None

    def forward(self, x):
        logits = self.gate(x)  # [B, S, E]

        def _moe(x, logits, w_up, w_down, *, top_k):
            e = logits.shape[-1]
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            # exact top-k mask from indices (a >=threshold compare would
            # select every tied expert, e.g. all of them on the uniform
            # probs a zero/padding token produces)
            idx = jax.lax.top_k(probs, top_k)[1]            # [B, S, k]
            mask = jnp.sum(jax.nn.one_hot(idx, e, dtype=probs.dtype),
                           axis=-2)
            mask = jnp.minimum(mask, 1.0)
            gates = probs * mask
            gates = gates / jnp.maximum(
                jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
            # dense dispatch: every expert on every token, gated sum.
            # w_up/w_down sharded on e -> per-shard partial experts; the
            # final contraction over e all-reduces over 'ep'.
            h = jnp.einsum("bsh,ehf->besf", x, w_up)
            h = jax.nn.gelu(h)
            y = jnp.einsum("besf,efh->besh", h, w_down)
            out = jnp.einsum("bse,besh->bsh", gates.astype(y.dtype), y)
            # GShard aux loss: E * sum_e (frac tokens routed to e *
            # mean gate prob of e)
            frac = jnp.mean(mask, axis=(0, 1))
            imp = jnp.mean(probs, axis=(0, 1))
            aux = e * jnp.sum(frac / top_k * imp)
            return out, aux.astype(x.dtype)

        def _moe_capacity(x, logits, w_up, w_down, *, top_k, cap_factor):
            """GShard top-k capacity dispatch (Lepikhin et al. 2020,
            algorithm in fig. 6): one-hot dispatch/combine einsums with
            per-expert capacity C and drop-overflow. Static shapes; the
            ep-sharded [E, C, H] buffers make the dispatch einsum the
            cross-expert shuffle (XLA picks the collective)."""
            b, s, hdim = x.shape
            e = logits.shape[-1]
            n = b * s
            cap = max(1, int(np.ceil(cap_factor * top_k * n / e)))
            xf = x.reshape(n, hdim)
            probs = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1).reshape(n, e)
            combine, dispatch, topi = _capacity_combine(xf, probs, top_k,
                                                        cap)
            buf = jnp.einsum("nec,nh->ech", dispatch, xf)
            h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", buf, w_up))
            y = jnp.einsum("ecf,efh->ech", h, w_down)
            out = jnp.einsum("nec,ech->nh", combine.astype(y.dtype), y)
            aux = _gshard_aux(probs, topi)
            return out.reshape(b, s, hdim), aux.astype(x.dtype)

        if self.dispatch_mode == "alltoall":
            out, aux = self._forward_alltoall(x, logits)
        elif self.dispatch_mode == "capacity":
            out, aux = apply_op("moe_ffn_capacity", _moe_capacity, x,
                                logits, self.w_up, self.w_down,
                                top_k=self.top_k,
                                cap_factor=self.capacity_factor)
        else:
            out, aux = apply_op("moe_ffn", _moe, x, logits, self.w_up,
                                self.w_down, top_k=self.top_k)
        from ..nn.aux_loss import emit_aux_loss

        emit_aux_loss(self, aux * self.aux_weight)
        return out

    def _forward_alltoall(self, x, logits):
        """Explicit GShard a2a dispatch under shard_map over 'ep' (see
        module docstring): tokens batch-sharded, experts local, two
        lax.all_to_all around the expert FFN."""
        from ..distributed import topology

        mesh = topology.get_global_mesh()
        axis = self.shard_axis
        ep = mesh.shape.get(axis, 1)
        e, top_k, cf = self.num_experts, self.top_k, self.capacity_factor
        if ep <= 1:
            raise ValueError(
                "dispatch_mode='alltoall' needs a global mesh with "
                f"{axis!r} > 1 (set_global_mesh(build_mesh(ep=...)))")
        if e % ep:
            raise ValueError(f"num_experts={e} must divide over "
                             f"{axis}={ep} for all_to_all dispatch")
        # tokens stay sharded over the data axes TOO (GShard groups =
        # product of data axes x ep; the a2a rides only the ep sub-axis)
        # — no per-step data->ep resharding. Shares shard_batch's axis
        # derivation so the incoming batch layout always matches.
        from ..distributed.topology import data_axes as _data_axes

        tok_axes = tuple(ax for ax in _data_axes(mesh)
                         if ax != axis) + (axis,)
        groups = int(np.prod([mesh.shape[ax] for ax in tok_axes]))
        b = int(x.shape[0])
        if b % groups:
            raise ValueError(f"batch {b} must be divisible by the token "
                             f"shard count {groups} (axes {tok_axes})")

        def local_fn(x, logits, w_up, w_down):
            # x: [B/groups, S, H] (groups = data axes x ep shards);
            # w_up/w_down: [E/ep, ...] (local experts)
            b_loc, s, hdim = x.shape
            n = b_loc * s
            cap = max(1, int(np.ceil(cf * top_k * n / e)))
            xf = x.reshape(n, hdim)
            probs = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1).reshape(n, e)
            combine, dispatch, topi = _capacity_combine(xf, probs, top_k,
                                                        cap)
            buf = jnp.einsum("nec,nh->ech", dispatch, xf)  # [E, C, H]
            # shard r keeps experts [r*E/ep, (r+1)*E/ep): swap the
            # expert dim across shards, stacking every shard's tokens
            # for my experts along capacity
            buf = jax.lax.all_to_all(buf, axis, split_axis=0,
                                     concat_axis=1, tiled=True)
            h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", buf, w_up))
            y = jnp.einsum("ecf,efh->ech", h, w_down)
            y = jax.lax.all_to_all(y, axis, split_axis=1, concat_axis=0,
                                   tiled=True)              # [E, C, H]
            out = jnp.einsum("nec,ech->nh", combine.astype(y.dtype), y)
            aux = jax.lax.pmean(_gshard_aux(probs, topi), tok_axes)
            return out.reshape(b_loc, s, hdim), aux.astype(x.dtype)

        def _a2a(x, logits, w_up, w_down):
            tok = P(tok_axes, None, None)
            wsp = P(axis, None, None)
            fn = shard_map(local_fn, mesh=mesh,
                           in_specs=(tok, tok, wsp, wsp),
                           out_specs=(tok, P()),
                           check_vma=False)
            return fn(x, logits, w_up, w_down)

        from ..core.dispatch import in_trace

        if not in_trace():
            # eager values sit committed on one device; move them onto
            # the mesh IN PLACE (value-preserving, keeps tape identity —
            # the eager-collective placement pattern of collective.py)
            from jax.sharding import NamedSharding

            def _place(t, spec):
                if not isinstance(t._value, jax.core.Tracer):
                    t._value = jax.device_put(t._value,
                                              NamedSharding(mesh, spec))

            _place(x, P())
            _place(logits, P())
            _place(self.w_up, P(axis))
            _place(self.w_down, P(axis))
        # cache key must discriminate everything the closure captures:
        # the mesh's token-shard group count, and the routing params
        # (top_k / capacity_factor / num_experts) — two layers differing
        # only in top_k would otherwise share the cached jit
        return apply_op(
            f"moe_ffn_a2a_{axis}{ep}_g{groups}_m{id(mesh)}"
            f"_k{top_k}_cf{cf}_e{e}",
            _a2a, x, logits, self.w_up, self.w_down)
