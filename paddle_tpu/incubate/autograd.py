"""Functional autograd transforms (beyond the eager tape) — jvp/vjp/hessian
(reference: python/paddle/autograd/functional.py in later revs; here they
are direct jax transforms over functionalized callables)."""
import jax

from ..core import dispatch
from ..core.tensor import Tensor


def _functionalize(fn):
    def pure(*arrays):
        with dispatch.trace_mode():
            out = fn(*[Tensor(a, stop_gradient=True) for a in arrays])
            if isinstance(out, (tuple, list)):
                return tuple(o._value if isinstance(o, Tensor) else o for o in out)
            return out._value if isinstance(out, Tensor) else out

    return pure


def vjp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._value for x in xs]
    out, vjp_fn = jax.vjp(_functionalize(func), *arrs)
    if v is None:
        import jax.numpy as jnp

        v = jnp.ones_like(out)
    else:
        v = v._value if isinstance(v, Tensor) else v
    grads = vjp_fn(v)
    return Tensor(out), [Tensor(g) for g in grads]


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._value for x in xs]
    if v is None:
        import jax.numpy as jnp

        tangents = [jnp.ones_like(a) for a in arrs]
    else:
        vs = v if isinstance(v, (list, tuple)) else [v]
        tangents = [t._value if isinstance(t, Tensor) else t for t in vs]
    out, tangent_out = jax.jvp(_functionalize(func), tuple(arrs), tuple(tangents))
    return Tensor(out), Tensor(tangent_out)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._value for x in xs_list]
    jac = jax.jacrev(_functionalize(func), argnums=tuple(range(len(arrs))))(*arrs)
    if not isinstance(xs, (list, tuple)):
        return Tensor(jac[0])
    return [Tensor(j) for j in jac]


def hessian(func, xs, create_graph=False, allow_unused=False):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [x._value for x in xs_list]
    hess = jax.hessian(_functionalize(func), argnums=tuple(range(len(arrs))))(*arrs)
    if not isinstance(xs, (list, tuple)):
        return Tensor(hess[0][0])
    return hess
