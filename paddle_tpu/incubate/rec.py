"""Sparse recommendation models: wide&deep and DeepFM over PS embeddings.

North-star "Sparse" config (BASELINE.md): wide&deep / DeepFM training with
the sparse embedding path. The reference ships these as PaddleRec configs
on top of distributed_lookup_table + the brpc PS (SURVEY §2.6, §2.9); here
the lookup is paddle_tpu.distributed.ps.sparse_embedding (host-side C++
tables) and the dense tower is ordinary paddle_tpu.nn running on TPU.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import ps


class WideDeep(nn.Layer):
    """wide&deep: wide = linear over sparse one-hot (a 1-dim embedding),
    deep = MLP over concatenated slot embeddings."""

    def __init__(self, client, slot_names, emb_dim=8, hidden=(64, 32),
                 wide_table=0, deep_table=1):
        super().__init__()
        self.client = client
        self.slots = list(slot_names)
        self.emb_dim = emb_dim
        self.wide_table = wide_table
        self.deep_table = deep_table
        layers = []
        in_dim = emb_dim * len(self.slots)
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.deep = nn.Sequential(*layers)

    def forward(self, slot_ids):
        # slot_ids: dict slot -> int64 [batch, max_per]
        wide_logit = 0.0
        deep_parts = []
        for s in self.slots:
            ids = slot_ids[s]
            wide_logit = wide_logit + ps.sparse_embedding(
                ids, self.client, self.wide_table, pooling="sum")
            deep_parts.append(ps.sparse_embedding(
                ids, self.client, self.deep_table, pooling="sum"))
        deep_in = paddle.concat(deep_parts, axis=-1)
        logit = self.deep(deep_in) + wide_logit
        return logit.squeeze(-1)


class DeepFM(nn.Layer):
    """DeepFM: FM second-order interactions over slot embeddings + first
    order (1-dim table) + deep MLP, shared embeddings."""

    def __init__(self, client, slot_names, emb_dim=8, hidden=(64, 32),
                 first_table=0, emb_table=1):
        super().__init__()
        self.client = client
        self.slots = list(slot_names)
        self.emb_dim = emb_dim
        self.first_table = first_table
        self.emb_table = emb_table
        layers = []
        in_dim = emb_dim * len(self.slots)
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.deep = nn.Sequential(*layers)

    def forward(self, slot_ids):
        first = 0.0
        embs = []
        for s in self.slots:
            ids = slot_ids[s]
            first = first + ps.sparse_embedding(
                ids, self.client, self.first_table, pooling="sum")
            embs.append(ps.sparse_embedding(
                ids, self.client, self.emb_table, pooling="sum"))
        # FM: 0.5 * ((sum v)^2 - sum v^2), summed over emb dim
        stack = paddle.stack(embs, axis=1)            # [b, slots, dim]
        sum_sq = paddle.square(stack.sum(axis=1))
        sq_sum = paddle.square(stack).sum(axis=1)
        fm = 0.5 * (sum_sq - sq_sum).sum(axis=-1, keepdim=True)
        deep_in = paddle.concat(embs, axis=-1)
        logit = self.deep(deep_in) + fm + first
        return logit.squeeze(-1)


def make_ps_tables(emb_dim=8, optimizer="adagrad", lr=0.05):
    """Standard 2-table layout: table 0 = 1-dim (wide/first-order),
    table 1 = emb_dim (deep/FM embeddings)."""
    return [
        ps.TableConfig("wide", is_sparse=True, emb_dim=1,
                       optimizer=optimizer, lr=lr, seed=1),
        ps.TableConfig("deep_emb", is_sparse=True, emb_dim=emb_dim,
                       optimizer=optimizer, lr=lr, seed=2),
    ]


def synthetic_ctr_files(path, n_files=2, rows_per_file=512, n_users=100,
                        n_items=200, seed=0):
    """Write slot-format CTR data ('label user:id item:id item:id') with a
    learnable structure: label = 1 iff (user+item) even for the first item."""
    rng = np.random.RandomState(seed)
    files = []
    for fi in range(n_files):
        fp = f"{path}/ctr_{fi}.txt"
        with open(fp, "w") as f:
            for _ in range(rows_per_file):
                u = rng.randint(0, n_users)
                items = rng.randint(0, n_items, rng.randint(1, 4))
                label = int((u + items[0]) % 2 == 0)
                toks = [f"user:{u}"] + [f"item:{i}" for i in items]
                f.write(f"{label} " + " ".join(toks) + "\n")
        files.append(fp)
    return files
