"""paddle.incubate.optimizer (reference: python/paddle/incubate/optimizer/
lookahead.py, modelaverage.py) — re-exports of the wrapper optimizers."""
from ...optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = ["LookAhead", "ModelAverage"]
