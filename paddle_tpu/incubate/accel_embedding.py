"""Accelerator-resident sparse embedding — the HeterPS/BoxPS capability
(reference: paddle/fluid/framework/fleet/heter_ps/ hashtable.h +
heter_comm.h + optimizer.cuh.h, ps_gpu_wrapper.cc: billions of sparse
rows held ON the accelerator boxes so the training loop never round-trips
to a CPU parameter server).

TPU-native redesign: no hash table and no RPC — the table is one dense
[capacity, emb_dim] parameter ROW-SHARDED over a mesh axis; feature ids
hash (multiply-shift, mod capacity) into rows; lookups are XLA gathers
and the backward is a scatter-add, all inside the one compiled SPMD
train step, with the gradient/update traffic riding ICI instead of
PCIe/brpc. Collisions are accepted exactly as in the reference's
mod-sharded accessors — capacity is provisioned above the live id count.
"""
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..core.dispatch import apply_op
from ..core.tensor import Tensor

def hash_ids(ids, capacity):
    """Deterministic id -> row mapping (murmur-style 32-bit finalizer
    then mod; the framework's dtype policy is 32-bit, so the mix stays
    in uint32)."""
    def _h(ids, *, cap):
        x = ids.astype(jnp.uint32)
        x = x * jnp.uint32(0x9E3779B1)
        x = x ^ (x >> jnp.uint32(15))
        x = x * jnp.uint32(0x85EBCA77)
        x = x ^ (x >> jnp.uint32(13))
        return (x % jnp.uint32(cap)).astype(jnp.int32)

    return apply_op("hash_ids", _h, ids, cap=int(capacity))


class AccelSparseEmbedding(nn.Layer):
    """Sharded on-device embedding table with hashed ids.

    shard_axis: mesh axis holding the rows ('mp' pairs with the
    tensor-parallel layout; 'sharding' spreads over the ZeRO group).
    Adam/Adagrad-style optimizers update only touched rows in effect
    (zero gradient rows have zero moments), matching the reference's
    per-row sparse optimizers.
    """

    def __init__(self, capacity, emb_dim, shard_axis="mp",
                 init_range=0.05, pad_id=None, name=None):
        super().__init__()
        self.capacity = int(capacity)
        self.emb_dim = int(emb_dim)
        self.pad_id = pad_id
        self.weight = self.create_parameter(
            [self.capacity, self.emb_dim],
            default_initializer=nn.initializer.Uniform(-init_range,
                                                       init_range))
        # row-sharded over the chosen mesh axis (spmd.build_train_step
        # honors mp_spec for placement + keeps the update sharded)
        self.weight.mp_spec = P(shard_axis)

    def forward(self, ids):
        rows = hash_ids(ids, self.capacity)
        emb = nn.functional.embedding(rows, self.weight)
        if self.pad_id is not None:
            def _mask(emb, ids, *, pad):
                return emb * (ids != pad)[..., None].astype(emb.dtype)

            emb = apply_op("accel_emb_pad_mask", _mask, emb, ids,
                           pad=int(self.pad_id))
        return emb
