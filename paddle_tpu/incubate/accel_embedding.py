"""Accelerator-resident sparse embedding — the HeterPS/BoxPS capability
(reference: paddle/fluid/framework/fleet/heter_ps/ hashtable.h +
heter_comm.h + optimizer.cuh.h, ps_gpu_wrapper.cc: billions of sparse
rows held ON the accelerator boxes so the training loop never round-trips
to a CPU parameter server).

TPU-native redesign: no RPC — the table is one dense
[capacity, emb_dim] parameter ROW-SHARDED over a mesh axis; lookups are
XLA gathers and the backward is a scatter-add, all inside the one
compiled SPMD train step, with the gradient/update traffic riding ICI
instead of PCIe/brpc. Two id->row policies:

- ``hashed`` (fully in-graph): ids hash (multiply-shift, mod capacity)
  into rows inside the trace; collisions are accepted — capacity must
  be provisioned above the live id count.
- ``exact`` (KeyAccessor): the reference's accessor semantics
  (framework/fleet/heter_ps/hashtable.h exact-key probing,
  distributed/table/common_sparse_table.cc entry admission) live
  HOST-side, mirroring the reference split where key->offset resolution
  is CPU accessor work and the accelerator holds values by offset: an
  exact key->row dict with a free list (two colliding ids always get
  DISTINCT rows), ``entry_attr`` ProbabilityEntry/CountFilterEntry
  admission gating insertion, and LRU eviction when full. Row
  translation happens at data-ingestion time (``assign_rows``), so the
  compiled train step still sees static int32 row indices.
"""
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..core.dispatch import apply_op, in_trace
from ..core.tensor import Tensor

def hash_ids(ids, capacity):
    """Deterministic id -> row mapping (murmur-style 32-bit finalizer
    then mod; the framework's dtype policy is 32-bit, so the mix stays
    in uint32)."""
    def _h(ids, *, cap):
        x = ids.astype(jnp.uint32)
        x = x * jnp.uint32(0x9E3779B1)
        x = x ^ (x >> jnp.uint32(15))
        x = x * jnp.uint32(0x85EBCA77)
        x = x ^ (x >> jnp.uint32(13))
        return (x % jnp.uint32(cap)).astype(jnp.int32)

    return apply_op("hash_ids", _h, ids, cap=int(capacity))


def _admission_hash(keys):
    """Deterministic per-key uniform in [0, 1) for ProbabilityEntry —
    reproducible across runs and ranks (the reference draws from the
    table's RNG; keying the draw off the id itself keeps every rank's
    admission decision identical without communication)."""
    x = np.asarray(keys, np.uint64)
    x = (x ^ (x >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
    x = (x ^ (x >> np.uint64(33))) * np.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> np.uint64(33))
    return (x >> np.uint64(11)).astype(np.float64) / float(1 << 53)


class KeyAccessor:
    """Host-side exact key -> row map with admission + LRU eviction
    (reference: heter_ps/hashtable.h exact-key probing +
    common_sparse_table.cc accessor admission via entry_attr).

    - two colliding ids ALWAYS occupy distinct rows (rows come from a
      free list, not a hash);
    - ``entry`` (ProbabilityEntry / CountFilterEntry) gates NEW key
      insertion; non-admitted keys resolve to row -1 (zero embedding,
      no update) while their observation counts still accumulate;
    - when the table is full the least-recently-used key is evicted
      (the reference's shrink()); evicted (key, row) pairs are reported
      via ``take_evicted`` so callers can re-init those rows.
    """

    def __init__(self, capacity, entry=None):
        self.capacity = int(capacity)
        self.entry = entry
        self.key_to_row = {}
        self._free = list(range(self.capacity - 1, -1, -1))
        self._counts = {}
        self._last_use = {}
        self._clock = 0
        self._evicted = []

    def _admit(self, key):
        if self.entry is None:
            return True
        kind = self.entry._to_attr().split(":")[0]
        if kind == "probability_entry":
            return _admission_hash(key) < self.entry.probability
        if kind == "count_filter_entry":
            return self._counts.get(key, 0) >= self.entry.count
        return True

    def _alloc_row(self, key):
        if not self._free:
            lru_key = min(self.key_to_row, key=self._last_use.__getitem__)
            row = self.key_to_row.pop(lru_key)
            self._last_use.pop(lru_key)
            self._evicted.append((lru_key, row))
            self._free.append(row)
        row = self._free.pop()
        self.key_to_row[key] = row
        return row

    def assign(self, ids):
        """Training-time id -> row translation with admission; returns
        int32 rows, -1 where the key is not (yet) admitted."""
        ids_arr = np.asarray(ids)
        rows = np.empty(ids_arr.shape, np.int32)
        flat_ids = ids_arr.ravel()
        flat_rows = rows.ravel()
        self._clock += 1
        for i, key in enumerate(flat_ids.tolist()):
            row = self.key_to_row.get(key)
            if row is None:
                self._counts[key] = self._counts.get(key, 0) + 1
                if self._admit(key):
                    row = self._alloc_row(key)
            if row is None:
                flat_rows[i] = -1
            else:
                self._last_use[key] = self._clock
                flat_rows[i] = row
        return rows

    def lookup(self, ids):
        """Inference-time translation: no admission, unknown keys -> -1."""
        ids_arr = np.asarray(ids)
        rows = np.asarray([self.key_to_row.get(k, -1)
                           for k in ids_arr.ravel().tolist()], np.int32)
        return rows.reshape(ids_arr.shape)

    def take_evicted(self):
        out, self._evicted = self._evicted, []
        return out

    def __len__(self):
        return len(self.key_to_row)


class AccelSparseEmbedding(nn.Layer):
    """Sharded on-device embedding table (see module docstring).

    shard_axis: mesh axis holding the rows ('mp' pairs with the
    tensor-parallel layout; 'sharding' spreads over the ZeRO group).
    Adam/Adagrad-style optimizers update only touched rows in effect
    (zero gradient rows have zero moments), matching the reference's
    per-row sparse optimizers.

    mode='hashed' (default): ids hash to rows inside the trace.
    mode='exact': ids resolve through the exact ``KeyAccessor``
    (``self.accessor``) — call ``assign_rows(ids)`` at data-ingestion
    time and feed the returned rows to ``forward``; eager calls with
    raw ids translate automatically. Unadmitted/unknown keys (-1 rows)
    produce zero embeddings and receive no gradient.
    """

    def __init__(self, capacity, emb_dim, shard_axis="mp",
                 init_range=0.05, pad_id=None, name=None, mode="hashed",
                 entry=None):
        super().__init__()
        self.capacity = int(capacity)
        self.emb_dim = int(emb_dim)
        self.pad_id = pad_id
        if mode not in ("hashed", "exact"):
            raise ValueError(f"mode must be 'hashed' or 'exact', got {mode!r}")
        self.mode = mode
        self.init_range = float(init_range)
        self._reinit_rng = np.random.default_rng(0xACCE1)
        self.last_evicted = []
        self.accessor = KeyAccessor(capacity, entry) if mode == "exact" \
            else None
        if entry is not None and mode != "exact":
            raise ValueError("entry admission needs mode='exact' (hashed "
                             "rows have no key identity to admit)")
        self.weight = self.create_parameter(
            [self.capacity, self.emb_dim],
            default_initializer=nn.initializer.Uniform(-init_range,
                                                       init_range))
        # row-sharded over the chosen mesh axis (spmd.build_train_step
        # honors mp_spec for placement + keeps the update sharded)
        self.weight.mp_spec = P(shard_axis)

    def _translate(self, ids, admit):
        """ids -> rows on host; pad ids pin to -1 before touching the
        accessor (a pad must neither be admitted nor counted)."""
        ids_np = np.asarray(ids._value if isinstance(ids, Tensor) else ids)
        if self.pad_id is not None:
            live = ids_np != self.pad_id
            rows = np.full(ids_np.shape, -1, np.int32)
            if live.any():
                sel = ids_np[live]
                rows[live] = (self.accessor.assign(sel) if admit
                              else self.accessor.lookup(sel))
        else:
            rows = (self.accessor.assign(ids_np) if admit
                    else self.accessor.lookup(ids_np))
        return rows

    def _reinit_evicted(self):
        """Reset rows the accessor just evicted: a newly admitted key
        must start from a FRESH embedding, not the evicted key's trained
        vector (reference: common_sparse_table.cc re-initializes values
        on insert). Rows are also recorded in ``self.last_evicted`` so a
        training loop can zero its optimizer moments for them (moments
        live with the optimizer, out of this layer's reach)."""
        self.last_evicted = []
        evicted = self.accessor.take_evicted()
        if not evicted:
            return
        rows = np.asarray([r for _, r in evicted], np.int32)
        fresh = self._reinit_rng.uniform(
            -self.init_range, self.init_range,
            (len(rows), self.emb_dim)).astype(np.float32)
        w = self.weight._value
        self.weight._value = w.at[rows].set(
            jnp.asarray(fresh, dtype=w.dtype))
        self.last_evicted = rows.tolist()

    def assign_rows(self, ids):
        """Host-side exact translation (mode='exact'): admits new keys
        per the entry policy and returns int32 rows (-1 = unadmitted)
        ready to feed into the compiled train step. Rows freed by LRU
        eviction are re-initialized before the step sees them."""
        if self.accessor is None:
            raise RuntimeError("assign_rows requires mode='exact'")
        rows = self._translate(ids, admit=True)
        self._reinit_evicted()
        return Tensor(jnp.asarray(rows), stop_gradient=True)

    def forward(self, ids):
        if self.mode == "exact":
            if in_trace():
                # traced inputs must already be rows (assign_rows ran at
                # ingestion) — raw ids cannot be translated in-graph.
                # assign_rows returns int32; raw feature ids are int64,
                # so a dtype check catches the silent-clamp misuse of
                # feeding untranslated ids into the compiled step.
                val = ids._value if isinstance(ids, Tensor) else ids
                if jnp.issubdtype(val.dtype, jnp.integer) and \
                        val.dtype != jnp.int32:
                    raise TypeError(
                        "mode='exact' traced forward expects int32 row "
                        "indices from assign_rows(); got raw "
                        f"{val.dtype} ids — translate them at data-"
                        "ingestion time with assign_rows()")
                rows = ids
            else:
                # eval/inference must not mutate the table: admission +
                # LRU touch only while training (reference accessors
                # admit on push, not on pull)
                rows_np = self._translate(ids, admit=self.training)
                if self.training:
                    self._reinit_evicted()
                rows = Tensor(jnp.asarray(rows_np), stop_gradient=True)

            def _gather_masked(rows, w):
                safe = jnp.where(rows < 0, 0, rows)
                emb = w[safe]
                return emb * (rows >= 0)[..., None].astype(emb.dtype)

            return apply_op("accel_emb_exact", _gather_masked, rows,
                            self.weight)
        rows = hash_ids(ids, self.capacity)
        emb = nn.functional.embedding(rows, self.weight)
        if self.pad_id is not None:
            def _mask(emb, ids, *, pad):
                return emb * (ids != pad)[..., None].astype(emb.dtype)

            emb = apply_op("accel_emb_pad_mask", _mask, emb, ids,
                           pad=int(self.pad_id))
        return emb
