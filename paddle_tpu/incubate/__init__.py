"""paddle.incubate (reference: python/paddle/fluid/incubate/: fleet v1 API,
auto_checkpoint)."""
from . import autograd  # noqa: F401
from .checkpoint import auto_checkpoint  # noqa: F401
