"""paddle.incubate (reference: python/paddle/fluid/incubate/: fleet v1 API,
auto_checkpoint; python/paddle/incubate/optimizer: LookAhead,
ModelAverage)."""
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from .checkpoint import auto_checkpoint  # noqa: F401
