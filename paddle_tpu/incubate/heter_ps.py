"""Heterogeneous PS training: CPU-resident sparse tables + accelerator
dense compute in ONE compiled step.

Reference: the HeterPS / downpour architecture
(paddle/fluid/framework/fleet/heter_ps/heter_comm.h, ps_gpu_wrapper.cc,
distributed/table/common_sparse_table.cc): enormous embedding tables
live on CPU parameter servers with per-row optimizers; the accelerator
runs the dense net, pulling embeddings forward and pushing gradients
back each step.

TPU-native redesign: the pull is a ``jax.pure_callback`` and the push an
ordered ``io_callback`` inside the SAME jitted train step — XLA's host
callback machinery replaces the reference's PCIe pull/push streams, and
the PS table's own per-row optimizer (sgd/adagrad in native/ps_core.cc)
applies the update, exactly the downpour split: sparse on host, dense on
device. Works under jit/pjit; eager calls go straight through.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import apply_op

__all__ = ["HeterPSEmbedding"]


class HeterPSEmbedding(nn.Layer):
    """Embedding whose table lives in a PS client (host), trained through
    the PS table's own per-row optimizer.

    client: a ps.LocalPSClient / ps.RpcPSClient / CommunicatorClient
    holding a sparse table at ``table_idx`` with ``emb_dim`` columns.
    forward(ids): [*, S] int -> [*, S, emb_dim] float32; gradients are
    pushed to the PS inside the compiled backward.
    """

    _uid_counter = 0

    def __init__(self, client, table_idx, emb_dim, scale_grad=1.0):
        super().__init__()
        self.client = client
        self.table_idx = int(table_idx)
        self.emb_dim = int(emb_dim)
        self.scale_grad = float(scale_grad)
        # _ps_embed is a per-instance closure over (client, table_idx,
        # dim): the fn_key convention requires state-capturing ops to
        # discriminate the cache key, else a second instance with the
        # same table_idx would silently serve this instance's table.
        HeterPSEmbedding._uid_counter += 1
        self._uid = HeterPSEmbedding._uid_counter
        # Autodiff prunes the vjp of a subgraph no differentiable input
        # feeds; ids are ints, so WITHOUT this zero-valued trainable
        # anchor the backward push would be eliminated as dead code and
        # the PS rows would silently never train.
        self._anchor = self.create_parameter(
            [], default_initializer=nn.initializer.Constant(0.0))
        client_ref = client
        tid, dim, scale = self.table_idx, self.emb_dim, self.scale_grad

        def _pull_host(ids_np, _anchor_np):
            # dedup repeated ids per batch (reference: heter_comm.h
            # pull/push batching — a wide&deep batch repeats hot ids
            # heavily, and the PS round-trip is the boundary that
            # dominates): pull each unique id once, scatter back
            ids_flat = np.asarray(ids_np).ravel()
            uniq, inverse = np.unique(ids_flat, return_inverse=True)
            vals = np.asarray(client_ref.pull_sparse(tid, uniq),
                              np.float32)[inverse]
            return vals.reshape(tuple(np.asarray(ids_np).shape) + (dim,))

        def _push_host(ids_np, grad_np):
            # aggregate gradients per unique id host-side, ONE push
            ids_flat = np.asarray(ids_np).ravel()
            g = np.asarray(grad_np, np.float32).reshape(len(ids_flat), dim)
            uniq, inverse = np.unique(ids_flat, return_inverse=True)
            agg = np.zeros((len(uniq), dim), np.float32)
            np.add.at(agg, inverse, g)
            client_ref.push_sparse(tid, uniq, agg * scale)

        # side-effecting callbacks cannot carry a replicated sharding
        # under the SPMD partitioner — pin the push to one device (the
        # host talks to the PS once per step, like the reference's
        # rank-0 push stream)
        from jax.sharding import SingleDeviceSharding

        cb_sharding = SingleDeviceSharding(jax.devices()[0])

        @jax.custom_vjp
        def _ps_embed(ids, anchor):
            # pure_callback keeps the SPMD partitioner happy (an ordered
            # io_callback's token trips its replicated-sharding check);
            # freshness is protected by threading ``anchor`` — a
            # trainable carry value — through the callback OPERANDS, so
            # XLA cannot hoist the pull out of a scanned train loop as
            # loop-invariant. CSE within one step is harmless: the PS
            # only mutates in the backward push.
            shape = tuple(ids.shape) + (dim,)
            e = jax.pure_callback(
                _pull_host, jax.ShapeDtypeStruct(shape, jnp.float32),
                ids, anchor)
            return e + anchor.astype(e.dtype) * 0.0

        def _fwd(ids, anchor):
            return _ps_embed(ids, anchor), ids

        def _bwd(ids, g):
            # ordered: the push must not be elided or reordered past the
            # next step's pull (the reference's push stream sync)
            jax.experimental.io_callback(_push_host, None, ids, g,
                                         ordered=True,
                                         sharding=cb_sharding)
            return (jnp.zeros(ids.shape, jax.dtypes.float0),
                    jnp.zeros((), jnp.float32))

        _ps_embed.defvjp(_fwd, _bwd)
        self._ps_embed = _ps_embed

    def forward(self, ids):
        return apply_op(self._op_name, self._ps_embed, ids, self._anchor)

    @property
    def _op_name(self):
        return f"heter_ps_embed_t{self.table_idx}_u{self._uid}"

    def __del__(self):
        # the per-uid cache key means each instance owns its cached jit,
        # whose closure pins the PS client — release it with the layer
        try:
            from ..core.dispatch import evict_ops

            evict_ops(self._op_name)
        except Exception:
            pass  # interpreter shutdown
