"""Auto-checkpoint (reference: python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:71 AutoCheckpointChecker — epoch-granular train-state
snapshots to a shared FS for preemptible-cluster resume).
"""
import json
import os
import time


class TrainEpochRange:
    """``for epoch in auto_checkpoint.train_epoch_range(N, save_dir=...)``:
    resumes from the last finished epoch recorded in the range's meta."""

    def __init__(self, max_epoch_num, name="default", save_dir=None,
                 checkpoint_inter=None, model=None, optimizer=None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        self.save_dir = save_dir or os.environ.get(
            "PADDLE_TPU_CHECKPOINT_DIR", f"/tmp/paddle_tpu_autockpt/{name}")
        self._model = model
        self._optimizer = optimizer
        self._meta_path = os.path.join(self.save_dir, "meta.json")
        self._start = 0
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                meta = json.load(f)
            self._start = meta.get("next_epoch", 0)
            ckpt = os.path.join(self.save_dir, "ckpt")
            if self._model is not None and os.path.exists(ckpt + ".pdparams"):
                from .. import framework

                self._model.set_state_dict(framework.load(ckpt + ".pdparams"))
                if self._optimizer is not None and os.path.exists(ckpt + ".pdopt"):
                    self._optimizer.set_state_dict(framework.load(ckpt + ".pdopt"))

    def __iter__(self):
        for epoch in range(self._start, self.max_epoch_num):
            yield epoch
            self._save(epoch)

    def _save(self, epoch):
        os.makedirs(self.save_dir, exist_ok=True)
        ckpt = os.path.join(self.save_dir, "ckpt")
        if self._model is not None:
            from .. import framework

            framework.save(self._model.state_dict(), ckpt + ".pdparams")
            if self._optimizer is not None:
                framework.save(self._optimizer.state_dict(), ckpt + ".pdopt")
        with open(self._meta_path, "w") as f:
            json.dump({"next_epoch": epoch + 1, "ts": time.time()}, f)


class auto_checkpoint:
    train_epoch_range = TrainEpochRange
