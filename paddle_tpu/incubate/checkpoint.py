"""Auto-checkpoint (reference: python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:71 AutoCheckpointChecker — epoch-granular train-state
snapshots to a shared FS for preemptible-cluster resume).

Resilience semantics (paddle_tpu.resilience):
- every write is atomic (tmp + os.replace): a crash mid-save never
  corrupts the resume state;
- ``meta.json`` keeps a one-generation backup (``meta.json.bak``); a
  corrupt/truncated meta falls back to the backup, and failing that the
  range restarts cleanly instead of crashing;
- SIGTERM/SIGINT preemption is honored at the epoch boundary: the
  epoch's snapshot is saved, a resumable marker is written, and the
  process exits 143 (128+SIGTERM) so the scheduler reschedules; the
  restarted range resumes from the recorded epoch.
"""
import json
import os
import time
import warnings

from ..resilience import chaos, preemption
from ..resilience.checkpoint import atomic_write_json


def _load_meta(meta_path):
    """-> meta dict from meta.json, falling back to meta.json.bak; None
    when neither is usable (fresh start)."""
    for path in (meta_path, meta_path + ".bak"):
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            continue
        except (OSError, json.JSONDecodeError, ValueError) as e:
            warnings.warn(
                f"auto_checkpoint: {path} unreadable ({e}); "
                f"falling back to the last good snapshot")
    return None


class TrainEpochRange:
    """``for epoch in auto_checkpoint.train_epoch_range(N, save_dir=...)``:
    resumes from the last finished epoch recorded in the range's meta."""

    def __init__(self, max_epoch_num, name="default", save_dir=None,
                 checkpoint_inter=None, model=None, optimizer=None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        self.save_dir = save_dir or os.environ.get(
            "PADDLE_TPU_CHECKPOINT_DIR", f"/tmp/paddle_tpu_autockpt/{name}")
        self._model = model
        self._optimizer = optimizer
        self._meta_path = os.path.join(self.save_dir, "meta.json")
        self._start = 0
        meta = _load_meta(self._meta_path)
        if meta is not None:
            self._start = int(meta.get("next_epoch", 0))
            ckpt = os.path.join(self.save_dir, "ckpt")
            if self._model is not None and os.path.exists(ckpt + ".pdparams"):
                from .. import framework

                self._model.set_state_dict(framework.load(ckpt + ".pdparams"))
                if self._optimizer is not None and os.path.exists(ckpt + ".pdopt"):
                    self._optimizer.set_state_dict(framework.load(ckpt + ".pdopt"))
        # a previous incarnation's preemption marker means this restart
        # IS the resume — consume it so a clean finish leaves no marker
        if preemption.read_resume_marker(self.save_dir) is not None:
            preemption.clear_resume_marker(self.save_dir)

    def __iter__(self):
        import signal as signal_mod

        # SIGTERM only (the scheduler's preemption signal); SIGINT
        # stays a hard KeyboardInterrupt for interactive runs
        handler = preemption.get_preemption_handler()
        uninstall_after = not handler._installed
        handler.install(signals=(signal_mod.SIGTERM,))
        try:
            for epoch in range(self._start, self.max_epoch_num):
                chaos.hit("train.epoch")
                yield epoch
                self._save(epoch)
                if handler.requested:
                    # save-and-exit at the epoch boundary: snapshot is
                    # on disk, marker makes the restart resumable, 143
                    # tells the scheduler this was a graceful preemption
                    preemption.write_resume_marker(
                        self.save_dir, step=epoch + 1,
                        extra={"name": self.name})
                    handler.clear()  # handled; a driver catching the
                    # exit and re-entering must not loop forever
                    raise preemption.PreemptedExit(step=epoch + 1)
        finally:
            if uninstall_after:
                # SIGTERM outside the range must kill the process again
                handler.uninstall()

    def _save(self, epoch):
        os.makedirs(self.save_dir, exist_ok=True)
        chaos.hit("autockpt.save")
        ckpt = os.path.join(self.save_dir, "ckpt")
        if self._model is not None:
            from .. import framework  # framework.save is atomic

            framework.save(self._model.state_dict(), ckpt + ".pdparams")
            if self._optimizer is not None:
                framework.save(self._optimizer.state_dict(), ckpt + ".pdopt")
        # keep the previous good meta as .bak before publishing the new
        # one — both writes atomic, so every crash point leaves at least
        # one parseable meta on disk
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path, "rb") as f:
                    old = f.read()
                json.loads(old)  # only back up a *good* meta
                from ..resilience.checkpoint import atomic_write_bytes

                atomic_write_bytes(self._meta_path + ".bak", old)
            except (OSError, json.JSONDecodeError, ValueError):
                pass
        atomic_write_json(self._meta_path,
                          {"next_epoch": epoch + 1, "ts": time.time()})


class auto_checkpoint:
    train_epoch_range = TrainEpochRange
