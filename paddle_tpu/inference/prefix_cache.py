"""Content-addressed KV prefix cache (ROADMAP item 2, PR 19).

Real decode traffic is dominated by shared prefixes — system prompts,
few-shot templates, multi-turn history. Prefill cost is O(prompt) per
request even when the first k tokens (and therefore their KV rows,
which depend only on the token prefix and the per-sequence features)
are identical across requests. This module turns that O(prompt) into
O(suffix): token prefixes are hashed at page-aligned boundaries, the
resulting KV pages live once in the engine's refcounted page pool
(:class:`decode._KVSlots`), and a hit installs them into a fresh slot
by reference — copy-on-write page sharing — so only the uncached
suffix ever runs through a model program.

**Chain hashing.** The prefix hash at boundary ``k + page`` extends
the hash at ``k``::

    h_0       = sha256("pfx0:" + feature_digest)
    h_{i+1}   = sha256(h_i || tokens[i*page : (i+1)*page].tobytes())

so hashing every boundary of a P-token prompt is one linear pass, and
two prompts share a cache entry exactly when their token pages AND
their feature bytes agree (KV rows are a function of both under the
DecodeModel contract — a feature-skewed hit would install foreign KV).

**Two tiers.** The in-memory tier maps ``hash -> (n_tokens, page ids)``
into the engine's page pool with LRU eviction under a byte budget.
The optional persistent tier reuses the PR 10 artifact-store machinery
(`PADDLE_TPU_PREFIX_DIR`): entries are PR 17 kv-snapshot blocks under
an :class:`~paddle_tpu.serialize.artifact_store.ArtifactKey` built
from model fingerprint + weights digest + quant + mesh + prefix hash,
so a fresh replica — or a PR 18 prefill-pool replica — inherits the
fleet's warm prefixes with zero prefill work. A block whose header
identity skews from this replica (foreign weights, quant, mesh, or
page geometry) is REFUSED exactly like a snapshot resume would be:
counted, never installed — wrong-model KV must never decode garbage.

Env knobs:
    PADDLE_TPU_PREFIX_DIR        persistent tier root (unset = memory
                                 tier only)
    PADDLE_TPU_PREFIX_MAX_BYTES  byte budget for BOTH tiers (in-memory
                                 page bytes; artifact-store gc cap);
                                 default 256 MiB
    PADDLE_TPU_PREFIX_DISABLE    "1" disables prefix caching entirely

Concurrency: the page pool is only ever mutated under the owning
engine's lock (the scheduler thread); every method documented as
"pool-mutating" REQUIRES the caller to hold it. The cache's own lock
only guards the entry map and counters so stats exposition never
blocks the decode loop.
"""
import hashlib
import os
import threading

import numpy as np

from ..resilience.retry import _env_int
from ..serialize import artifact_store as _artifacts
from . import wire_spec as _wire_spec

__all__ = ["PrefixCache", "feature_seed", "prefix_hashes"]

# Machine-checked lock order (tools/tracelint.py --concurrency): the
# cache lock is a leaf under the engine lock — entry-map updates run
# inside the scheduler's pool mutations, never the reverse.
# tpu-lock-order: DecodeEngine._lock < PrefixCache._lock  # pool -> entry map

_DEFAULT_MAX_BYTES = 256 << 20


def feature_seed(features):
    """Digest of a request's feature arrays (dtype/shape/bytes) — the
    chain-hash seed. KV rows are a function of tokens AND features
    under the DecodeModel contract, so feature-skewed requests must
    never share prefix entries."""
    h = hashlib.sha256(b"pfx-feat:")
    for f in features:
        a = np.ascontiguousarray(np.asarray(f))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.digest()


def prefix_hashes(prompt_i32, page_len, seed=b""):
    """Chain hashes at every full-page boundary of ``prompt_i32``:
    ``[(page_len, h1), (2*page_len, h2), ...]`` (hex digests), longest
    last. ``hash(p[:k+page])`` extends ``hash(p[:k])`` — one linear
    pass hashes every boundary."""
    prompt = np.ascontiguousarray(np.asarray(prompt_i32, dtype=np.int32))
    h = hashlib.sha256(b"pfx0:" + seed)
    out = []
    n_pages = prompt.size // int(page_len)
    for i in range(n_pages):
        page = prompt[i * page_len:(i + 1) * page_len]
        h = hashlib.sha256(h.digest() + page.tobytes())
        out.append(((i + 1) * int(page_len), h.hexdigest()))
    return out


class PrefixCache:
    """Content-addressed prefix store over an engine's page pool (see
    module docstring). ``slots`` is the owning engine's
    :class:`decode._KVSlots`; ``identity_fn`` returns the replica
    identity dict (fingerprint/weights/quant/mesh) for the persistent
    tier — called lazily because the fingerprint is."""

    def __init__(self, slots, identity_fn=None, max_bytes=None,
                 store_dir=None, name="prefix"):
        self._slots = slots
        self.page_len = int(slots.page_len)
        self._identity_fn = identity_fn
        self.name = name
        if max_bytes is None:
            max_bytes = _env_int("PADDLE_TPU_PREFIX_MAX_BYTES",
                                 _DEFAULT_MAX_BYTES)
        self.max_bytes = int(max_bytes)
        page_bytes = max(1, slots.page_bytes())
        self.max_pages = max(1, self.max_bytes // page_bytes)
        if store_dir is None:
            store_dir = os.environ.get("PADDLE_TPU_PREFIX_DIR") or None
        self._store = None
        if store_dir:
            self._store = _artifacts.ArtifactStore(
                store_dir, max_bytes=self.max_bytes)
        self._lock = threading.Lock()
        self._entries = {}   # hash hex -> [n_tokens, [page ids], tick]
        self._tick = 0
        self._published = set()  # hashes already pushed to the store
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.store_hits = 0
        self.store_refused = 0

    # ------------------------------------------------------------ restrace
    # The runtime sanitizer pairs these: every page set the cache
    # retains must be dropped (eviction / clear) before teardown.
    # tpu-resource: acquires=prefix_entry
    def _hold(self, key):
        return key

    # tpu-resource: releases=prefix_entry
    def _drop(self, key):
        return key

    # ------------------------------------------------------- memory tier
    def lookup(self, hashes):
        """Longest cached prefix among ``hashes`` (the chain, longest
        last) -> ``(n_tokens, page_ids)`` or None. Entry-map read only;
        the caller installs under the pool lock (scheduler thread, so
        the pages cannot be evicted in between — eviction only happens
        on the same thread, inside :meth:`insert`)."""
        with self._lock:
            for n_tokens, hx in reversed(hashes):
                e = self._entries.get(hx)
                if e is not None:
                    self._tick += 1
                    e[2] = self._tick
                    self.hits += 1
                    return e[0], list(e[1])
            self.misses += 1
            return None

    def insert(self, hx, n_tokens, pages):
        """Retain ``pages`` (ids in the pool) as the entry for ``hx``.
        POOL-MUTATING: caller holds the engine lock. Evicts LRU entries
        beyond the page budget. Returns the number of entries evicted
        (0 when ``hx`` was already cached — a duplicate insert retains
        nothing and evicts nothing)."""
        with self._lock:
            if hx in self._entries:
                return 0
            for pid in pages:
                self._slots.retain_page(pid)
            self._tick += 1
            self._entries[hx] = [int(n_tokens), list(pages), self._tick]
            self._hold(hx)
            evict = []
            while (sum(len(e[1]) for e in self._entries.values())
                    > self.max_pages and len(self._entries) > 1):
                lru = min(self._entries, key=lambda k: self._entries[k][2])
                if lru == hx and len(self._entries) == 1:
                    break
                evict.append((lru, self._entries.pop(lru)))
            for lru, e in evict:
                for pid in e[1]:
                    self._slots.drop_page(pid)
                self._drop(lru)
                self.evictions += 1
            return len(evict)

    def needs_publish(self, hx):
        """Would :meth:`publish` actually write ``hx``? Lets the engine
        skip the kv snapshot copy when there is no persistent tier or
        the prefix already shipped."""
        if self._store is None or _artifacts.disabled():
            return False
        with self._lock:
            return hx not in self._published

    def clear(self):
        """Drop every entry (pool-mutating: caller holds the engine
        lock) — engine close calls this so the page census drains."""
        with self._lock:
            entries = list(self._entries.items())
            self._entries.clear()
        for hx, e in entries:
            for pid in e[1]:
                self._slots.drop_page(pid)
            self._drop(hx)

    # --------------------------------------------------- persistent tier
    def _identity(self):
        ident = self._identity_fn() if self._identity_fn else None
        if not ident or not ident.get("fingerprint"):
            return None
        return ident

    def _store_key(self, hx, n_tokens, ident):
        sig = (("decode:prefix", (self.page_len, int(n_tokens))),
               (hx, ()),
               ("w" + ident["weights"][:16], ()))
        return _artifacts.ArtifactKey(
            ident["fingerprint"], int(n_tokens) // self.page_len, sig,
            mesh=ident["mesh"], quant=ident["quant"])

    def load_store(self, hashes, prompt_i32):
        """Longest persistent-tier prefix among ``hashes`` ->
        ``(hx, n_tokens, kv_arrays)`` or None. File IO — call WITHOUT
        the engine lock; the caller materializes pages under it via
        :meth:`install_arrays`. A block whose header identity skews
        from this replica is refused (counted), never installed."""
        if self._store is None or _artifacts.disabled():
            return None
        ident = self._identity()
        if ident is None:
            return None
        for n_tokens, hx in reversed(hashes):
            payload = self._store.get(self._store_key(hx, n_tokens, ident))
            if payload is None:
                continue
            got = self._check_block(payload, hx, n_tokens, ident,
                                    prompt_i32)
            if got is not None:
                with self._lock:
                    self.store_hits += 1
                return hx, n_tokens, got
        return None

    def _check_block(self, payload, hx, n_tokens, ident, prompt_i32):
        """PR 17 skew-refusal discipline over a prefix block: identity
        + geometry + content must all match, else refuse (a foreign
        KV prefix decodes garbage — a miss is always preferable)."""
        try:
            header, arrays, _ = _wire_spec.decode_kv_snapshot_off(payload)
        except Exception:  # noqa: BLE001 - corrupt block is a refusal
            with self._lock:
                self.store_refused += 1
            return None
        kv_spec = self._slots.kv_spec
        ok = (header.get("fingerprint") == ident["fingerprint"]
              and header.get("weights") == ident["weights"]
              and header.get("quant") == ident["quant"]
              and header.get("mesh") == ident["mesh"]
              and int(header.get("page_len", -1)) == self.page_len
              and int(header.get("pos", -1)) == int(n_tokens)
              and header.get("prefix_hash") == hx
              and len(arrays) == 2 + len(kv_spec))
        if ok:
            stored_prompt = arrays[0]
            ok = (stored_prompt.ndim == 1
                  and stored_prompt.size == int(n_tokens)
                  and np.array_equal(
                      stored_prompt,
                      np.asarray(prompt_i32[:n_tokens], dtype=np.int32)))
        if ok:
            kv = arrays[2:]
            for a, (tr, dt) in zip(kv, kv_spec):
                if (tuple(a.shape) != (int(n_tokens),) + tr
                        or a.dtype != dt):
                    ok = False
                    break
        if not ok:
            with self._lock:
                self.store_refused += 1
            return None
        return list(arrays[2:])

    def install_arrays(self, hx, n_tokens, kv_arrays):
        """Materialize a store-loaded prefix into pool pages and insert
        the entry. POOL-MUTATING: caller holds the engine lock.
        Returns the page id list."""
        pages = self._slots.pages_from_arrays(kv_arrays, n_tokens)
        with self._lock:
            if hx in self._entries:
                # raced ourselves via an identical in-flight prompt:
                # keep the existing entry, drop the fresh pages
                for pid in pages:
                    self._slots.drop_page(pid)
                e = self._entries[hx]
                return list(e[1])
            self._tick += 1
            self._entries[hx] = [int(n_tokens), list(pages), self._tick]
            self._hold(hx)
        return pages

    def publish(self, hx, n_tokens, prompt_i32, kv_copies):
        """Best-effort persistent publish (file IO — call WITHOUT the
        engine lock). The payload is a PR 17 kv-snapshot block whose
        header carries the full replica identity + page geometry —
        what :meth:`load_store` refuses on at the other end."""
        if self._store is None or _artifacts.disabled():
            return False
        with self._lock:
            if hx in self._published:
                return False
            self._published.add(hx)
        ident = self._identity()
        if ident is None:
            return False
        prompt = np.ascontiguousarray(
            np.asarray(prompt_i32[:n_tokens], dtype=np.int32))
        header = {
            "fingerprint": ident["fingerprint"],
            "weights": ident["weights"],
            "quant": ident["quant"],
            "mesh": ident["mesh"],
            "pos": int(n_tokens),
            "last_token": int(prompt[-1]),
            "n_generated": 0,
            "prompt_len": int(n_tokens),
            "page_len": self.page_len,
            "prefix_hash": hx,
        }
        arrays = [prompt, np.zeros((0,), np.int32)] + list(kv_copies)
        try:
            blob = _wire_spec.encode_kv_snapshot(header, arrays)
        except Exception:  # noqa: BLE001 - publish is best-effort
            return False
        return self._store.put(self._store_key(hx, n_tokens, ident), blob)

    # --------------------------------------------------------------- views
    def stats(self):
        with self._lock:
            pages = sum(len(e[1]) for e in self._entries.values())
            return {
                "entries": len(self._entries),
                "pages": pages,
                "max_pages": self.max_pages,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "store_hits": self.store_hits,
                "store_refused": self.store_refused,
                "persistent": self._store is not None,
            }
