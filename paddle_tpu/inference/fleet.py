"""Fleet tier: self-healing multi-replica serving (ROADMAP item 3).

Ties the pieces together into one operable unit::

    clients ──> FleetRouter ──┬──> serve_model replica 0
      (wire protocol,         ├──> serve_model replica 1   ── artifact
       any existing client)   └──> serve_model replica 2      store

    Fleet = ReplicaRegistry (heartbeats, eject/probe)
          + FleetRouter     (WFQ admission, shed-aware retry, drains)
          + supervisor      (respawn dead replicas, autoscale)

A :class:`Fleet` owns N replicas produced by a ``spawn_fn`` — either
:func:`subprocess_spawner` (a fresh ``serve_model`` process per
replica; with ``PADDLE_TPU_ARTIFACT_DIR`` set, respawn and scale-up are
warm: the PR 10 artifact store makes a new replica's whole bucket
ladder load instead of compile) or anything else returning a
:class:`ReplicaHandle`-shaped object (tests use in-process servers).

The supervisor thread:

- **respawns** replicas whose process died (SIGKILL, OOM, crash): the
  dead rid is deregistered and a replacement spawned and registered —
  the router routes around the corpse from the first failed heartbeat
  or I/O error, so the only client-visible effect is a few retryable
  status-2 replies, never a hang or a wrong tensor;
- **autoscales**: sustained admission-queue pressure (requests waiting
  in the router's fair gate, or deep per-replica engine queues) spawns
  a replica up to ``max_replicas``; a sustained idle fleet drains one
  replica (zero-drop: new work routes elsewhere, in-flight finishes)
  and stops it, down to ``min_replicas``.

``rolling_reload`` hot-swaps weights across the fleet one replica at a
time: drain -> wire cmd 4 reload -> undrain, so the fleet never has
fewer than N-1 replicas taking traffic and no request ever drops.

``pools`` disaggregates the fleet into phase pools (README
"Disaggregated serving"): ``Fleet(spawn_fn, pools={"prefill": 1,
"decode": 2})`` spawns phase-tagged replicas
(``registry.register(..., phase=...)``), buries and respawns each
pool's dead independently, and runs one :class:`Autoscaler` per pool
over pool-local signals only (:meth:`Fleet.pool_signals`): the
prefill controller sees admission-gate waiting (TTFT pressure), the
decode controller sees its own replicas' backlog plus KV-slot
saturation (inter-token pressure). A prefill burst therefore never
scales the decode pool, and vice versa. Without ``pools`` nothing
changes — one ``both`` pool, fleet-global signals, the 1-arg
``spawn_fn`` contract.

Env knobs (constructor kwargs win):
    PADDLE_TPU_FLEET_MIN_REPLICAS        (1)
    PADDLE_TPU_FLEET_MAX_REPLICAS        (4)
    PADDLE_TPU_FLEET_SUPERVISE_S         supervisor tick     (0.5)
    PADDLE_TPU_FLEET_SCALE_UP_PRESSURE   per-replica queued+
                                         waiting to add one  (4.0)
    PADDLE_TPU_FLEET_SCALE_DOWN_TICKS    consecutive idle
                                         ticks to remove one (20)
    PADDLE_TPU_FLEET_SPAWN_TIMEOUT_S     subprocess replica
                                         bind wait           (120)
(plus the ROUTER/REGISTRY knobs — see router.py / registry.py.)
"""
import os
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

from ..obs import metrics as obs_metrics
from .registry import EJECTED, ReplicaRegistry, _env_float, _env_int
from .router import FleetRouter, TenantPolicy, tenant_id  # noqa: F401
from .server import _read_all
from .wire_spec import CMD_RELOAD, CMD_STOP, REPLICA_PHASES

_M_RESPAWNS = obs_metrics.counter(
    "paddle_fleet_respawns_total",
    "Dead replicas replaced by the fleet supervisor")
_M_SCALE = obs_metrics.counter(
    "paddle_fleet_scale_events_total",
    "Autoscaler actions", labelnames=("direction",))
_M_POOL_REPLICAS = obs_metrics.gauge(
    "paddle_fleet_pool_replicas",
    "Live replicas per phase pool (refreshed each supervisor tick)",
    labelnames=("phase",))


class ReplicaHandle:
    """One spawned replica: its endpoint plus enough process handle to
    supervise it. ``proc`` is a subprocess.Popen or None (in-process
    replicas override :meth:`alive`/:meth:`stop`)."""

    def __init__(self, rid, host, port, proc=None):
        self.rid = rid
        self.host = host
        self.port = int(port)
        self.proc = proc

    @property
    def pid(self):
        return None if self.proc is None else self.proc.pid

    def alive(self):
        # a proc-less (in-process) handle counts as alive unless a
        # subclass says otherwise
        return self.proc is None or self.proc.poll() is None

    def stop(self, timeout=10.0):
        """Graceful stop: wire cmd 7, then wait, then SIGKILL."""
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=2.0) as s:
                s.settimeout(2.0)
                s.sendall(struct.pack("<IB", 1, CMD_STOP))
                (blen,) = struct.unpack("<I", _read_all(s, 4))
                _read_all(s, blen)
        except (OSError, ConnectionError):
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                try:
                    self.proc.wait(5.0)
                except subprocess.TimeoutExpired:
                    pass  # un-reapable zombie; the OS owns it now


# Portfile-dir lifecycle: a spawn's rendezvous dir lives exactly as
# long as the spawn attempt — _portdir_done on every path (the replica
# wrote its port into it; nothing reads it again). The TPU5xx lint and
# the restrace sanitizer both key on this pair.
# tpu-resource: acquires=tmp_dir
def _portdir_create():
    """One private dir for a replica's port rendezvous file."""
    return tempfile.mkdtemp(prefix="fleet-")


# tpu-resource: releases=tmp_dir
def _portdir_done(path):
    """Retire a port-rendezvous dir (bound, crashed, or timed out)."""
    shutil.rmtree(path, ignore_errors=True)


def subprocess_spawner(prefix, host="127.0.0.1", extra_env=None,
                       spawn_timeout=None, max_batch_size=8,
                       max_wait_ms=2.0, max_queue=256):
    """Build a ``spawn_fn`` that starts each replica as a fresh
    ``serve_model`` process (``python -m paddle_tpu.inference.fleet
    --replica ...``). Point ``PADDLE_TPU_ARTIFACT_DIR`` (or pass it via
    ``extra_env``) at a shared store to make every spawn warm."""
    timeout = (spawn_timeout if spawn_timeout is not None
               else _env_float("PADDLE_TPU_FLEET_SPAWN_TIMEOUT_S", 120.0))
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    # tpu-resource: acquires=tmp_dir releases=tmp_dir
    def spawn(rid, phase=None):
        portdir = _portdir_create()
        try:
            portfile = os.path.join(portdir, f"{rid}.port")
            env = dict(os.environ)
            env["PYTHONPATH"] = (repo + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            if extra_env:
                env.update(extra_env)
            argv = [sys.executable, "-m", "paddle_tpu.inference.fleet",
                    "--replica", prefix, portfile,
                    str(max_batch_size), str(max_wait_ms),
                    str(max_queue)]
            if phase:  # pooled fleets spawn phase-tagged replicas
                argv.append(phase)
            proc = subprocess.Popen(argv, env=env)
            t_end = time.monotonic() + timeout
            while time.monotonic() < t_end:
                if os.path.exists(portfile):
                    with open(portfile) as f:
                        return ReplicaHandle(rid, host, int(f.read()),
                                             proc=proc)
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {rid} exited rc={proc.returncode} "
                        "before binding")
                time.sleep(0.02)
            proc.kill()
            proc.wait()
            raise TimeoutError(f"replica {rid} did not bind within "
                               f"{timeout:.0f}s")
        finally:
            _portdir_done(portdir)

    return spawn


class Autoscaler:
    """Pure scale decision over one supervisor tick's observations
    (kept side-effect free so tests drive it directly):
    ``decide(n_replicas, waiting, backlog)`` -> +1 / 0 / -1 where
    ``waiting`` is requests queued in the router's fair gate and
    ``backlog`` is the summed per-replica (router in-flight + engine
    queue depth)."""

    def __init__(self, min_replicas=None, max_replicas=None,
                 scale_up_pressure=None, scale_down_ticks=None):
        self.min_replicas = max(1, (
            min_replicas if min_replicas is not None
            else _env_int("PADDLE_TPU_FLEET_MIN_REPLICAS", 1)))
        self.max_replicas = (
            max_replicas if max_replicas is not None
            else _env_int("PADDLE_TPU_FLEET_MAX_REPLICAS", 4))
        self.scale_up_pressure = (
            scale_up_pressure if scale_up_pressure is not None
            else _env_float("PADDLE_TPU_FLEET_SCALE_UP_PRESSURE", 4.0))
        self.scale_down_ticks = (
            scale_down_ticks if scale_down_ticks is not None
            else _env_int("PADDLE_TPU_FLEET_SCALE_DOWN_TICKS", 20))
        self._idle_ticks = 0

    def decide(self, n_replicas, waiting, backlog):
        if n_replicas < self.min_replicas:
            return 1
        pressure = waiting + backlog
        per_replica = pressure / max(1, n_replicas)
        if per_replica >= self.scale_up_pressure \
                and n_replicas < self.max_replicas:
            self._idle_ticks = 0
            return 1
        if pressure == 0:
            self._idle_ticks += 1
            if self._idle_ticks >= self.scale_down_ticks \
                    and n_replicas > self.min_replicas:
                self._idle_ticks = 0
                return -1
        else:
            self._idle_ticks = 0
        return 0


class _Pool:
    """One phase pool's supervision state: a spawn callable already
    bound to the phase, an independent :class:`Autoscaler`, and the
    pool's rid counter. The poolless (legacy) fleet is one ``both``
    pool with ``replica-{n}`` rids; pooled fleets name replicas
    ``{phase}-{n}`` so pool membership survives in logs and stats."""

    def __init__(self, phase, spawn, autoscaler, n0, legacy=False):
        self.phase = phase
        self.spawn = spawn
        self.autoscaler = autoscaler
        self.n0 = n0
        self.legacy = legacy
        self.next_rid = 0

    def new_rid(self):
        n = self.next_rid
        self.next_rid += 1
        return f"replica-{n}" if self.legacy else f"{self.phase}-{n}"


class Fleet:
    """Spawn, register, route, supervise (see module docstring).

    ``spawn_fn(rid) -> ReplicaHandle`` produces replicas;
    :func:`subprocess_spawner` builds the production one. With
    ``supervise=False`` nothing respawns or autoscales (tests drive
    :meth:`supervise_once` manually).

    ``pools`` disaggregates the fleet into phase pools::

        Fleet(spawn_fn, pools={"prefill": 1, "decode": 2})
        Fleet(None, pools={
            "prefill": {"replicas": 1, "spawn": spawn_p,
                        "autoscaler": Autoscaler(max_replicas=2)},
            "decode":  {"replicas": 2, "spawn": spawn_d},
        })

    With ``pools``, the shared ``spawn_fn`` (or a pool's own ``spawn``)
    is called ``fn(rid, phase)`` so spawners can start phase-shaped
    replicas (:func:`subprocess_spawner`'s spawn takes the second
    argument); each pool gets its own :class:`Autoscaler` — pass one
    via the dict form, int-form pools build a default — fed only
    pool-local signals (:meth:`pool_signals`); a dead replica respawns
    into its own pool. Without ``pools`` nothing changes: one ``both``
    pool, the 1-arg ``spawn_fn`` contract, fleet-global autoscaling."""

    def __init__(self, spawn_fn, replicas=None, tenants=(),
                 registry=None, router_kwargs=None, autoscaler=None,
                 supervise=True, supervise_interval=None, pools=None):
        self._spawn_fn = spawn_fn
        self.autoscaler = autoscaler or Autoscaler()
        self._pools = self._build_pools(spawn_fn, replicas, pools)
        self.registry = registry or ReplicaRegistry()
        self.router = FleetRouter(self.registry, tenants=tenants,
                                  own_registry=False,
                                  **(router_kwargs or {}))
        self._lock = threading.Lock()
        self._handles = {}  # rid -> ReplicaHandle
        self._phases = {}   # rid -> phase (pool membership)
        self._closed = threading.Event()
        self.respawns = 0
        for pool in self._pools.values():
            for _ in range(pool.n0):
                self._spawn_one(pool.phase)
        self._thread = None
        if supervise:
            interval = (supervise_interval if supervise_interval is not None
                        else _env_float("PADDLE_TPU_FLEET_SUPERVISE_S", 0.5))
            self._interval = interval
            self._thread = threading.Thread(target=self._supervise_loop,
                                            name="fleet-supervisor",
                                            daemon=True)
            self._thread.start()

    def _build_pools(self, spawn_fn, replicas, pools):
        if pools is None:
            if spawn_fn is None:
                raise ValueError("Fleet needs a spawn_fn")
            n0 = (replicas if replicas is not None
                  else self.autoscaler.min_replicas)
            return {"both": _Pool("both", spawn_fn, self.autoscaler,
                                  n0, legacy=True)}
        out = {}
        for phase, cfg in pools.items():
            if phase not in REPLICA_PHASES:
                raise ValueError(
                    f"unknown pool phase {phase!r}; "
                    f"expected one of {REPLICA_PHASES}")
            if isinstance(cfg, dict):
                fn = cfg.get("spawn") or spawn_fn
                scaler = cfg.get("autoscaler") or Autoscaler()
                n0 = cfg.get("replicas")
            else:
                fn, scaler, n0 = spawn_fn, Autoscaler(), int(cfg)
            if fn is None:
                raise ValueError(
                    f"pool {phase!r} has no spawn callable (pass a "
                    "shared spawn_fn or a per-pool 'spawn')")
            if n0 is None:
                n0 = scaler.min_replicas
            # pooled contract: the spawn callable sees the phase so it
            # can start a phase-shaped replica (warmup ladder, health)
            bound = (lambda rid, _fn=fn, _ph=phase: _fn(rid, _ph))
            out[phase] = _Pool(phase, bound, scaler, n0)
        if not out:
            raise ValueError("pools must name at least one phase")
        return out

    @property
    def port(self):
        """The router's client-facing port."""
        return self.router.port

    def handles(self):
        with self._lock:
            return dict(self._handles)

    def pools(self):
        """Live pool membership: ``{phase: [rid, ...]}`` (sorted)."""
        with self._lock:
            out = {phase: [] for phase in self._pools}
            for rid in sorted(self._phases):
                out[self._phases[rid]].append(rid)
        return out

    # ------------------------------------------------------------ scaling
    def _only_pool(self):
        if len(self._pools) == 1:
            return next(iter(self._pools))
        raise ValueError("phase required for a multi-pool fleet "
                         f"(pools: {sorted(self._pools)})")

    def _spawn_one(self, phase=None):
        pool = self._pools[phase if phase is not None
                           else self._only_pool()]
        with self._lock:
            rid = pool.new_rid()
        handle = pool.spawn(rid)
        with self._lock:
            # a close() that raced this spawn (it can take the whole
            # subprocess startup) must not leak an orphan replica: the
            # handle table is already cleared, so stop the newborn
            # instead of inserting it
            aborted = self._closed.is_set()
            if not aborted:
                self._handles[rid] = handle
                self._phases[rid] = pool.phase
        if aborted:
            handle.stop()
            return None
        self.registry.register(rid, handle.host, handle.port,
                               pid=handle.pid, phase=pool.phase)
        return rid

    def _remove_one(self, rid, drain_deadline=10.0):
        """Zero-drop scale-down: drain (router stops routing, replica
        announces it, in-flight finishes), then stop."""
        self.router.drain(rid, deadline_s=drain_deadline)
        with self._lock:
            handle = self._handles.pop(rid, None)
            self._phases.pop(rid, None)
        self.registry.deregister(rid)
        if handle is not None:
            handle.stop()

    def _members(self, phase):
        """Locked read of one pool's live rids, sorted."""
        with self._lock:
            return sorted(r for r, p in self._phases.items()
                          if p == phase)

    def scale_to(self, n, phase=None):
        """Imperative scale of one pool (the autoscalers do this on
        pressure). ``phase`` may be omitted for a single-pool fleet.
        Scaling a pure pool to zero is legal: the router degrades the
        affected handoffs to colocated serving on the surviving pool
        (README "Disaggregated serving")."""
        phase = phase if phase is not None else self._only_pool()
        if phase not in self._pools:
            raise ValueError(f"no such pool: {phase!r}")
        while True:
            members = self._members(phase)
            current = len(members)
            if current < n:
                if self._spawn_one(phase) is None:  # closing: stop
                    return
            elif current > n:
                self._remove_one(members[-1])
            else:
                return

    # --------------------------------------------------------- supervisor
    def pool_signals(self, phase, views=None):
        """One pool's autoscaling signals: ``(waiting, backlog)``.

        Admission-gate waiting is attributed to the pool that runs a
        request's FIRST leg — the prefill pool when one exists (gate
        pressure is TTFT pressure), else the colocated ``both`` pool,
        else the decode pool — so a prefill burst never scales the
        decode pool. Backlog sums router in-flight + engine queue
        depth over this pool's replicas only; the decode pool
        additionally counts KV-slot saturation (a replica reporting
        zero free slots adds one scale-up-pressure unit — inter-token
        pressure exists even when its admission queues are shallow).
        The poolless fleet's single ``both`` pool sees the fleet-global
        signals, exactly the pre-pool behavior."""
        if views is None:
            views = self.registry.snapshot()
        first_leg = ("prefill" if "prefill" in self._pools
                     else "both" if "both" in self._pools else "decode")
        waiting = 0
        if phase == first_leg:
            waiting = sum(t["waiting"]
                          for t in self.router.gate.stats().values())
        with self._lock:
            phases = dict(self._phases)
        backlog = 0
        for v in views:
            if phases.get(v.rid, "both") != phase:
                continue
            backlog += v.inflight + v.queue_depth
            if phase == "decode" and v.free_slots == 0:
                backlog += self._pools[phase].autoscaler.scale_up_pressure
        return waiting, backlog

    def supervise_once(self):
        """One supervisor tick: bury+respawn dead replicas into their
        own pool, then ask each pool's autoscaler over pool-local
        signals. Runs unlocked except for handle-table reads and
        writes — spawning (seconds) must not block drains or stats."""
        if self._closed.is_set():
            return {"dead": 0, "action": 0, "waiting": 0,
                    "backlog": 0, "ejected": 0, "pools": {}}
        with self._lock:
            dead = [(rid, h, self._phases.get(rid))
                    for rid, h in self._handles.items() if not h.alive()]
        for rid, handle, phase in dead:
            with self._lock:
                self._handles.pop(rid, None)
                self._phases.pop(rid, None)
            self.registry.deregister(rid)
            try:
                handle.stop(timeout=0.1)  # reap the corpse
            except Exception:  # noqa: BLE001 — already dead
                pass
            if phase not in self._pools:  # pool was reconfigured away
                phase = next(iter(self._pools))
            if self._spawn_one(phase) is not None:
                self.respawns += 1
                _M_RESPAWNS.inc()
        views = self.registry.snapshot()
        ejected = sum(v.state == EJECTED for v in views)
        total_waiting = sum(t["waiting"]
                            for t in self.router.gate.stats().values())
        total_backlog = sum(v.inflight + v.queue_depth for v in views)
        pools_out = {}
        net_action = 0
        for phase, pool in self._pools.items():
            waiting, backlog = self.pool_signals(phase, views=views)
            action = pool.autoscaler.decide(len(self._members(phase)),
                                            waiting, backlog)
            if action > 0:
                self._spawn_one(phase)
                _M_SCALE.inc(direction="up")
            elif action < 0:
                members = self._members(phase)
                if members:
                    self._remove_one(members[-1])
                    _M_SCALE.inc(direction="down")
            n_now = len(self._members(phase))
            _M_POOL_REPLICAS.set(n_now, phase=phase)
            net_action += action
            pools_out[phase] = {"replicas": n_now, "waiting": waiting,
                                "backlog": backlog, "action": action}
        return {"dead": len(dead), "action": net_action,
                "waiting": total_waiting, "backlog": total_backlog,
                "ejected": ejected, "pools": pools_out}

    def _supervise_loop(self):
        while not self._closed.wait(self._interval):
            try:
                self.supervise_once()
            except Exception:  # noqa: BLE001 — supervisor must survive
                # a failed spawn (transient exec error) must not kill
                # supervision; the next tick retries
                pass

    # ------------------------------------------------------------ reloads
    def rolling_reload(self, prefix=None, drain_deadline=10.0):
        """Hot weight swap across the fleet, one replica at a time,
        zero dropped requests: drain -> cmd 4 reload -> undrain. The
        fleet keeps serving on the other replicas throughout. Pooled
        fleets reload grouped by phase, still one replica at a time
        fleet-wide — a single-replica pool briefly empties, which the
        router covers by degrading its handoffs to colocated serving.
        Returns the per-replica reload JSON replies."""
        out = {}
        with self._lock:
            order = sorted(self._handles, key=lambda r: (
                self._phases.get(r, "both"), r))
            todo = [(r, self._handles[r]) for r in order]
        for rid, handle in todo:
            self.router.drain(rid, deadline_s=drain_deadline)
            try:
                payload = struct.pack("<B", CMD_RELOAD) + (
                    (prefix or "").encode("utf-8"))
                with socket.create_connection(
                        (handle.host, handle.port), timeout=300) as s:
                    s.settimeout(300)
                    s.sendall(struct.pack("<I", len(payload)) + payload)
                    (blen,) = struct.unpack("<I", _read_all(s, 4))
                    body = _read_all(s, blen)
                out[rid] = {"status": body[0],
                            "body": body[1:].decode("utf-8",
                                                    errors="replace")}
            finally:
                self.router.undrain(rid)
        return out

    # -------------------------------------------------------------- close
    def close(self):
        self._closed.set()
        if self._thread is not None:
            self._thread.join(5.0)
        self.router.stop()
        with self._lock:
            handles = list(self._handles.values())
            self._handles = {}
            self._phases = {}
        for h in handles:
            try:
                h.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self.registry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _replica_main(argv):
    """``python -m paddle_tpu.inference.fleet --replica PREFIX PORTFILE
    [max_batch max_wait_ms max_queue [phase]]`` — one serve_model
    replica that writes its bound port atomically and serves until
    cmd 7. ``phase`` tags the replica's pool (prefill | decode | both)
    in its cmd-3 health body."""
    prefix, portfile = argv[0], argv[1]
    max_batch = int(argv[2]) if len(argv) > 2 else 8
    max_wait_ms = float(argv[3]) if len(argv) > 3 else 2.0
    max_queue = int(argv[4]) if len(argv) > 4 else 256
    phase = argv[5] if len(argv) > 5 else None
    from .server import serve_model

    srv = serve_model(prefix, dynamic_batching=True,
                      max_batch_size=max_batch, max_wait_ms=max_wait_ms,
                      max_queue=max_queue, phase=phase)
    with open(portfile + ".tmp", "w") as f:
        f.write(str(srv.port))
    os.replace(portfile + ".tmp", portfile)
    srv._thread.join()  # serve until the stop command (cmd 7)
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--replica":
        sys.exit(_replica_main(sys.argv[2:]))
    print("usage: python -m paddle_tpu.inference.fleet --replica "
          "PREFIX PORTFILE [max_batch max_wait_ms max_queue [phase]]",
          file=sys.stderr)
    sys.exit(2)
