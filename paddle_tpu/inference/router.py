"""Front-tier fleet router (ROADMAP item 3 tentpole).

Speaks the exact PredictorServer wire protocol on its front socket, so
every existing client (Go/R/C, bench.py, plain sockets) points at the
router instead of a replica and nothing else changes. Behind it, a
:class:`~paddle_tpu.inference.registry.ReplicaRegistry` of ``serve_model``
replicas. Per cmd-1 infer request the router:

1. **admits** through a weighted-fair gate: per-tenant FIFO queues
   (tenant = the optional ``0x7E`` trailing wire field, see
   :func:`tenant_id`; untagged requests share the ``default`` tenant)
   scheduled by start-time fair queueing — each grant consumes
   ``1/weight`` of virtual time, so a noisy tenant saturating its queue
   cannot starve a polite one — over a bounded total concurrency; a
   tenant whose own queue is full is shed *immediately* (status 2,
   accounted to that tenant alone);
2. **routes** to the least-loaded routable replica (router in-flight +
   last heartbeat queue depth, warm-bucket count breaking ties toward
   replicas whose ladder is already compiled), chaos site
   ``fleet.route``;
3. **retries**: a replica answering the retryable status 2 (shed /
   quarantined / restarting) is retried on a *different* replica with
   bounded exponential backoff + jitter (the ``resilience/retry.py``
   shape); a replica that dies mid-request (connect/read error or
   timeout) is reported to the registry — poisoned, ejected, probed
   back in — and the request fails over to another replica immediately
   (no backoff: the failure was detected, not load-signalled);
4. **accounts**: per-tenant request/shed/deadline counters in
   ``paddle_tpu.obs`` and a serving-goodput ledger entry
   (``obs.goodput.SERVING_LEDGER``) per finished request.

The client contract under ANY single-replica failure is: every request
ends with status 0 (correct tensors) or status 2 (retryable) — never a
hang, never a wrong answer, never a status-1 error caused by fleet
topology. Status 1 is reserved for genuine request errors the replica
itself reported.

Draining (zero-drop reload / scale-down): :meth:`FleetRouter.drain`
marks a replica not-routable, optionally tells the replica itself (wire
cmd 8, so its own health announces ``accepting: false``), then waits
for the router's in-flight count on that replica to reach zero.
In-flight requests finish; new ones go elsewhere; nothing drops.

Stream resume (PR 17): for relayed decode streams the "fails over to
another replica" promise extends PAST the first token. The router
stamps its snapshot cadence into the forwarded decode field, retains
the newest kv-snapshot frame each replica interleaves into its stream
(stripped before clients that never opted in — their bytes are
identical with the feature on or off), and on a mid-stream replica
death re-drives the remainder on another replica via the kv_resume
command: delivered tokens are trimmed by sequence position (zero
duplicated, zero lost), the resumed suffix is bitwise what the dead
replica would have produced (the engine's solo-vs-batch contract), the
per-token deadline clock keeps running across the outage, and a
replica with a different model fingerprint / weights digest / quant
mode / mesh refuses the hand-off (status 2, tried elsewhere) instead
of decoding garbage. No snapshot yet, or every candidate refused →
today's status-2 terminal frame. The held snapshot is a DECLARED
kv_snapshot resource (``_snap_hold`` / ``_snap_release``): the TPU5xx
lint and the restrace census prove every relay path drops it.

Disaggregated serving (PR 18): when the fleet is split into phase
pools (``Fleet(pools=...)`` — the registry's health probes carry each
replica's ``phase``), a genuine decode stream is served as a
prefill->decode HANDOFF: the prefill leg runs the handoff-bit cmd 1 on
a prefill replica (one kv-snapshot frame + the first token back), the
first token goes straight to the client (TTFT never waits for decode
placement), and the decode leg seeds a decode replica via kv_resume —
retried once on a *different* decode replica, then on any surviving
replica (outcome ``degraded``). A pure pool with nothing routable
degrades the stream to plain colocated dispatch on whatever survives:
counted (``paddle_handoff_total{outcome="degraded"}``), logged, and
self-recovering. Every handoff path keeps the ok-or-retryable client
contract, and the handoff snapshot rides the same declared
kv_snapshot resource pair as stream resume.

Env knobs (constructor kwargs win):
    PADDLE_TPU_FLEET_RETRY_ATTEMPTS    total tries per request (3)
    PADDLE_TPU_FLEET_RETRY_BASE_S      first shed backoff      (0.05)
    PADDLE_TPU_FLEET_RETRY_MAX_S       shed backoff ceiling    (1.0)
    PADDLE_TPU_FLEET_MAX_INFLIGHT      fair-gate concurrency   (64)
    PADDLE_TPU_FLEET_TENANT_QUEUE      per-tenant waiting cap  (32)
    PADDLE_TPU_FLEET_ADMIT_TIMEOUT_S   deadline-less admission
                                       wait cap                (5.0)
    PADDLE_TPU_FLEET_BACKEND_TIMEOUT_S per-attempt reply cap   (30.0)
    PADDLE_TPU_FLEET_HANDOFF_TIMEOUT_S per-attempt prefill/
                                       decode handoff leg cap  (5.0)
    PADDLE_TPU_DECODE_SNAPSHOT_EVERY   resume-point cadence in
                                       tokens, 0 disables      (8)
"""
import hashlib
import json
import logging
import os
import random
import socket
import struct
import threading
import time

from ..obs import goodput as obs_goodput
from ..obs import metrics as obs_metrics
from ..obs import prometheus as obs_prometheus
from ..resilience import chaos
from ..resilience.retry import backoff_delays
from .registry import ReplicaRegistry, _env_float, _env_int
from .server import MAX_BODY_BYTES, BodyTooLarge, _read_all
# wire constants come from the ONE machine-readable spec (wire_spec.py;
# the --protocol lint fails on hardcoded wire literals here)
from .wire_spec import (CMD_DRAIN, CMD_HEALTH, CMD_INFER, CMD_KV_RESUME,
                        CMD_METRICS, CMD_STATS, CMD_STOP, DEADLINE_MARKER,
                        DECODE_HANDOFF_BIT, DECODE_MARKER,
                        DECODE_ONESHOT_BIT, DECODE_SNAPSHOT_EVERY_MASK,
                        DECODE_SNAPSHOT_EVERY_SHIFT, STATUS_ERROR,
                        STATUS_OK, STATUS_STREAM, TENANT_MARKER,
                        TRACE_MARKER, build_request,
                        decode_kv_snapshot_header, encode_arrays,
                        is_kv_snapshot)
from .wire_spec import STATUS_RETRYABLE as STATUS_OVERLOADED
from .wire_spec import decode_arrays_off as _decode_arrays_off

DEFAULT_TENANT = "default"

# Machine-checked lock order (tools/tracelint.py --concurrency):
# the fair gate's condition lock and the registry lock are LEAVES of
# the router — no router code path holds one while taking the other,
# and neither is ever held across socket I/O or a metrics bump.
# tpu-lock-order: FairGate._lock < Metric._lock  # shed accounting under the gate


def tenant_id(name):
    """Stable 64-bit wire id for a tenant name (sha256 prefix): clients
    compute it once and send it as the ``0x7E`` trailing field; router
    policies declare the same names."""
    return int.from_bytes(
        hashlib.sha256(str(name).encode("utf-8")).digest()[:8], "little")


class TenantPolicy:
    """Admission policy for one tenant: scheduling ``weight`` (shares
    of the fleet under contention), ``max_queue`` (bound on requests
    WAITING in the router for this tenant; overflow sheds immediately)
    and an optional ``slo_ms`` used for deadline-hit accounting when a
    request carries no explicit wire deadline."""

    def __init__(self, name, weight=1.0, max_queue=None, slo_ms=None):
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        self.name = str(name)
        self.weight = float(weight)
        self.max_queue = (max_queue if max_queue is not None
                          else _env_int("PADDLE_TPU_FLEET_TENANT_QUEUE", 32))
        self.slo_ms = slo_ms
        self.tid = tenant_id(self.name)


class ShedError(RuntimeError):
    """Router-side shed (wire status 2): tenant queue full, admission
    deadline expired, no routable replica, or retries exhausted."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


class _Waiter:
    __slots__ = ("finish", "seq", "granted")

    def __init__(self, finish, seq):
        self.finish = finish
        self.seq = seq
        self.granted = False


class _TenantState:
    __slots__ = ("policy", "queue", "vfinish", "granted", "shed")

    def __init__(self, policy):
        self.policy = policy
        self.queue = []  # FIFO of _Waiter
        self.vfinish = 0.0  # finish tag of the last admitted request
        self.granted = 0
        self.shed = 0


_M_SHEDS = obs_metrics.counter(
    "paddle_fleet_sheds_total",
    "Requests the router shed (wire status 2), by tenant and reason",
    labelnames=("tenant", "reason"))
_M_REQUESTS = obs_metrics.counter(
    "paddle_fleet_requests_total",
    "Requests finished by the router, by tenant and wire status",
    labelnames=("tenant", "status"))
_M_RETRIES = obs_metrics.counter(
    "paddle_fleet_retries_total",
    "Per-request replica retries, by cause (shed = status-2 rerouted "
    "with backoff, io = dead-replica failover, stream_resume = "
    "mid-stream decode failover re-driven from a kv snapshot, "
    "handoff = a disaggregated prefill or decode leg re-run on "
    "another replica)",
    labelnames=("cause",))
_M_DEADLINE = obs_metrics.counter(
    "paddle_fleet_deadline_total",
    "Deadline accounting at the router, by tenant and outcome",
    labelnames=("tenant", "outcome"))
_M_INFLIGHT = obs_metrics.gauge(
    "paddle_fleet_inflight",
    "Requests currently admitted through the router's fair gate")
_M_RESUMES = obs_metrics.counter(
    "paddle_decode_resumes_total",
    "Mid-stream decode failovers at the router, by outcome (ok = the "
    "stream was re-driven on another replica from a kv snapshot, "
    "refused = every candidate refused or failed the hand-off, "
    "no_snapshot = the replica died before any resume point existed)",
    labelnames=("outcome",))
_M_RESUME_SECONDS = obs_metrics.histogram(
    "paddle_decode_resume_seconds",
    "Replica-death-to-first-resumed-frame latency of successful "
    "mid-stream decode failovers")
_M_HANDOFF = obs_metrics.counter(
    "paddle_handoff_total",
    "Disaggregated prefill->decode handoffs at the router, by outcome "
    "(ok = first placement served the stream, retried = a prefill or "
    "decode leg was re-run before success, degraded = served "
    "colocated because a pure pool was empty or refused every "
    "attempt, failed = the client saw a retryable terminal after the "
    "handoff began)",
    labelnames=("outcome",))
_M_HANDOFF_SECONDS = obs_metrics.histogram(
    "paddle_handoff_seconds",
    "Prefill-snapshot-held to decode-replica-accepted latency of "
    "successful disaggregated handoffs")

_LOG = logging.getLogger("paddle_tpu.inference.router")


class FairGate:
    """Start-time weighted fair queueing over a bounded concurrency.

    ``acquire(tenant)`` blocks until one of the ``capacity`` permits is
    granted to this request in WFQ order, sheds immediately when the
    tenant's own waiting queue is at ``max_queue``, and sheds on
    timeout. Each grant advances the tenant's virtual finish tag by
    ``1/weight``; the waiter with the smallest finish tag among queue
    heads is granted first — the classic SFQ guarantee that a tenant's
    long-run share under contention is proportional to its weight,
    regardless of how hard another tenant storms."""

    def __init__(self, capacity, policies=(), default_policy=None):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants = {}  # tid -> _TenantState
        self._by_name = {}  # name -> _TenantState
        self._vtime = 0.0
        self._permits = self.capacity
        self._seq = 0
        self._default = default_policy or TenantPolicy(DEFAULT_TENANT)
        for p in policies:
            self._add(p)
        self._add(self._default)

    def _add(self, policy):
        st = _TenantState(policy)
        self._tenants.setdefault(policy.tid, st)
        self._by_name.setdefault(policy.name, st)

    def add_tenant(self, policy):
        with self._lock:
            self._add(policy)

    def _state_for(self, tid):
        # unknown tenant ids share the default tenant's queue/weight
        # (an unconfigured tenant must not mint itself a fresh share)
        if tid is None:
            return self._by_name[self._default.name]
        st = self._tenants.get(tid)
        return st if st is not None else self._by_name[self._default.name]

    def acquire(self, tid, timeout):
        """Admit one request for tenant id `tid` (None = default).
        Returns the tenant name. Raises :class:`ShedError` on a full
        tenant queue or timeout."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            st = self._state_for(tid)
            name = st.policy.name
            if len(st.queue) >= st.policy.max_queue:
                st.shed += 1
                raise ShedError("tenant_queue_full")
            start = max(self._vtime, st.vfinish)
            w = _Waiter(start + 1.0 / st.policy.weight, self._seq)
            self._seq += 1
            st.queue.append(w)
            try:
                while not w.granted:
                    self._grant_locked()
                    if w.granted:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ShedError("admission_timeout")
                    self._cond.wait(min(remaining, 0.5))
            except ShedError:
                st.queue.remove(w)
                st.shed += 1
                raise
            st.granted += 1
        _M_INFLIGHT.inc()
        return name

    def _grant_locked(self):
        """Hand out permits to queue heads in WFQ order (caller holds
        the lock)."""
        while self._permits > 0:
            best = None
            for st in self._tenants.values():
                if not st.queue:
                    continue
                head = st.queue[0]
                if (best is None
                        or (head.finish, head.seq)
                        < (best[1].finish, best[1].seq)):
                    best = (st, head)
            if best is None:
                return
            st, head = best
            st.queue.pop(0)
            head.granted = True
            self._permits -= 1
            self._vtime = max(self._vtime, head.finish - 1.0
                              / st.policy.weight)
            st.vfinish = head.finish
            self._cond.notify_all()

    def release(self):
        with self._cond:
            self._permits += 1
            self._grant_locked()
        _M_INFLIGHT.dec()

    def stats(self):
        with self._lock:
            return {st.policy.name: {
                "weight": st.policy.weight,
                "waiting": len(st.queue),
                "granted": st.granted,
                "shed": st.shed,
            } for st in self._by_name.values()}


def _split_meta(body):
    """Split a cmd-1 body into (arrays_bytes, fields) where
    arrays_bytes is the cmd byte + array payload (trailing fields
    EXCLUDED), fields is a list of (marker, raw8) in wire order, and
    tail is any unparsed remainder (an unknown marker stops the scan,
    mirroring the server; the bytes are preserved for forwarding);
    also extract (tenant_id, budget_s, trace_id)."""
    payload = body[1:]
    _, arrays_end = _decode_arrays_off(payload)
    off = arrays_end
    fields = []
    tid = budget = trace = None
    while len(payload) - off >= 9:
        marker = payload[off]
        raw = payload[off + 1:off + 9]
        if marker == DEADLINE_MARKER and budget is None:
            (ms,) = struct.unpack("<d", raw)
            budget = max(0.0, float(ms)) / 1000.0
        elif marker == TRACE_MARKER and trace is None:
            (t,) = struct.unpack("<Q", raw)
            trace = t or None
        elif marker == TENANT_MARKER and tid is None:
            (tid,) = struct.unpack("<Q", raw)
        elif marker == DECODE_MARKER:
            # a streaming decode request: kept in ``fields`` so it
            # forwards to the replica; its presence switches dispatch
            # into chunk-relay mode. Parsed here (not treated unknown)
            # so fields BEHIND it still split correctly.
            pass
        else:
            break
        fields.append((marker, raw))
        off += 9
    return (body[:1 + arrays_end], fields, payload[off:],
            tid, budget, trace)


class _Streamed:
    """Sentinel result of a relayed chunk stream: the reply frames
    already went to the client; only accounting remains."""

    __slots__ = ("status", "tokens", "max_gap_s", "replica_ok")

    def __init__(self, status, tokens, max_gap_s, replica_ok=True):
        self.status = status
        self.tokens = tokens
        self.max_gap_s = max_gap_s
        self.replica_ok = replica_ok


class _ClientGone(ConnectionError):
    """The CLIENT vanished mid-relay (its socket write failed): there
    is nobody to answer — the handler just closes."""


class FleetRouter:
    """TCP front tier over a :class:`ReplicaRegistry` (see module
    docstring). Construct with an existing registry (``own_registry=
    False``) or let it build one; ``tenants`` is an iterable of
    :class:`TenantPolicy`."""

    # tpu-resource: acquires=router_socket
    def __init__(self, registry=None, port=0, host="127.0.0.1",
                 tenants=(), max_inflight=None, retry_attempts=None,
                 retry_base=None, retry_max=None, admit_timeout=None,
                 backend_timeout=None, own_registry=None,
                 max_body=MAX_BODY_BYTES, rng=random.random,
                 snapshot_every=None, handoff_timeout=None):
        own = registry is None if own_registry is None else own_registry
        self.registry = registry if registry is not None \
            else ReplicaRegistry()
        self._own_registry = own
        self.retry_attempts = max(1, (
            retry_attempts if retry_attempts is not None
            else _env_int("PADDLE_TPU_FLEET_RETRY_ATTEMPTS", 3)))
        self.retry_base = (retry_base if retry_base is not None
                           else _env_float("PADDLE_TPU_FLEET_RETRY_BASE_S",
                                           0.05))
        self.retry_max = (retry_max if retry_max is not None
                          else _env_float("PADDLE_TPU_FLEET_RETRY_MAX_S",
                                          1.0))
        self.admit_timeout = (
            admit_timeout if admit_timeout is not None
            else _env_float("PADDLE_TPU_FLEET_ADMIT_TIMEOUT_S", 5.0))
        self.backend_timeout = (
            backend_timeout if backend_timeout is not None
            else _env_float("PADDLE_TPU_FLEET_BACKEND_TIMEOUT_S", 30.0))
        # per-attempt cap on one disaggregated handoff leg (prefill
        # run or decode placement): a stuck pool member must cost at
        # most this before the leg moves to another replica
        self.handoff_timeout = (
            handoff_timeout if handoff_timeout is not None
            else _env_float("PADDLE_TPU_FLEET_HANDOFF_TIMEOUT_S", 5.0))
        self.max_body = max_body
        # snapshot cadence stamped onto forwarded decode requests so
        # replicas interleave resume points into their streams; the
        # router holds the newest one and fails a broken stream over
        # to another replica. 0 disables router-managed resume.
        self.snapshot_every = min(DECODE_SNAPSHOT_EVERY_MASK, max(0, (
            snapshot_every if snapshot_every is not None
            else _env_int("PADDLE_TPU_DECODE_SNAPSHOT_EVERY", 8))))
        self._rng = rng
        self.gate = FairGate(
            max_inflight if max_inflight is not None
            else _env_int("PADDLE_TPU_FLEET_MAX_INFLIGHT", 64),
            policies=tenants)
        self._pools = {}  # rid -> [idle sockets]
        self._pools_lock = threading.Lock()
        self._stop = threading.Event()
        self._conns = {}  # handler thread -> socket
        self._conns_lock = threading.Lock()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve,
                                        name="fleet-router-accept",
                                        daemon=True)
        self._thread.start()

    # --------------------------------------------------------- membership
    def add_tenant(self, policy):
        self.gate.add_tenant(policy)

    # ----------------------------------------------------------- backend
    # Replica-connection lifecycle: every checkout comes from
    # _pool_get/_conn_open and every checked-out socket ends in exactly
    # one of _pool_put (clean reuse) or _conn_close (poison) — the
    # TPU5xx lint and the restrace sanitizer both key on these four.
    # tpu-resource: acquires=router_socket
    def _pool_get(self, rid):
        with self._pools_lock:
            pool = self._pools.get(rid)
            if pool:
                return pool.pop()
        return None

    # tpu-resource: acquires=router_socket
    def _conn_open(self, view):
        """Dial one replica connection. TCP_NODELAY is set before the
        socket escapes — a raise after the dial must close it, or the
        half-configured socket leaks."""
        sock = socket.create_connection((view.host, view.port),
                                        timeout=self.registry.dial_timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            sock.close()
            raise
        return sock

    # tpu-resource: releases=router_socket
    def _conn_close(self, sock):
        """Poison one checked-out replica connection (best-effort,
        never raises): timed-out, desynced, or client-gone sockets
        must die here, never return to the pool."""
        try:
            sock.close()
        except OSError:
            pass

    # tpu-resource: releases=router_socket
    def _pool_put(self, rid, sock):
        with self._pools_lock:
            if not self._stop.is_set():
                self._pools.setdefault(rid, []).append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _pool_drop(self, rid):
        with self._pools_lock:
            socks = self._pools.pop(rid, [])
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    # Kv-snapshot lifecycle: a relayed stream RETAINS at most one
    # resume point (a full KV copy — holding it past the stream's end
    # pins accelerator-sized buffers per request). Every hold comes
    # from _snap_hold and ends in exactly one _snap_release — the
    # TPU5xx lint and the restrace sanitizer both key on this pair.
    # tpu-resource: acquires=kv_snapshot
    def _snap_hold(self, blob):
        """Retain one kv-snapshot block as the stream's resume point."""
        return bytes(blob)

    # tpu-resource: releases=kv_snapshot
    def _snap_release(self, snap):
        """Drop a held resume point. The body is trivial on purpose:
        the declared acquire/release pair is what lets the static lint
        and the runtime census prove no relay path leaks a snapshot."""
        return None

    def _forward(self, view, frame, timeout, client_conn=None,
                 stream_ctx=None):
        """Send one framed request to replica `view` over a pooled
        connection; return the raw response body (status byte +
        payload). Raises OSError/ConnectionError/TimeoutError on a
        dead/stalled replica (the connection is NOT returned to the
        pool in that case — a desynced stream must never be reused).

        ``client_conn`` (streaming decode requests): if the first
        reply frame is a status-3 chunk, frames are RELAYED to the
        client until the terminal frame and a :class:`_Streamed`
        summary is returned instead of a body; from the first relayed
        byte on there is no retry (the client already consumed part of
        the stream) — a replica that dies mid-relay ends the stream
        with a status-2 terminal frame, so the client sees retryable,
        never truncated-but-ok. A normal single first frame (shed,
        error, one-shot reply) returns exactly like the plain path, so
        the caller's retry logic still applies to it."""
        sock = self._pool_get(view.rid)
        fresh = sock is None
        if fresh:
            sock = self._conn_open(view)
        hdr = b""
        t_send = time.monotonic()
        try:
            sock.settimeout(timeout)
            sock.sendall(frame)
            hdr = _read_all(sock, 4)
            (blen,) = struct.unpack("<I", hdr)
            body = _read_all(sock, blen)
        except socket.timeout:
            # a SLOW replica, not a dead stream: resending would
            # double-execute the request and double the latency —
            # surface the timeout (caller ejects + fails over)
            self._conn_close(sock)
            raise
        except (OSError, ConnectionError):
            self._conn_close(sock)
            if not fresh and not hdr:
                # the pooled connection was stale (closed by a replica
                # restart between requests — reset/EOF before any
                # reply byte): one transparent retry on a fresh dial.
                # Inference is read-only, so even the worst case (the
                # replica executed but died pre-reply) cannot corrupt
                # state, and a genuinely dead replica fails the fresh
                # dial immediately. Nothing was relayed yet, so this
                # is equally safe for the streaming path.
                return self._forward_fresh(view, frame, timeout,
                                           client_conn, stream_ctx)
            raise
        if body and body[0] == STATUS_STREAM:
            if client_conn is not None:
                return self._relay(view, sock, body, client_conn, timeout,
                                   t_send, stream_ctx)
            # a replica streaming at a NON-streaming dispatch (version
            # skew): the socket is mid-stream and desynced — poison it;
            # pooling it would corrupt the next request on this replica
            self._conn_close(sock)
            return body
        self._pool_put(view.rid, sock)
        return body

    @staticmethod
    def _chunk_tokens(body):
        """Token count of one chunk frame body (status + arrays)."""
        if len(body) <= 1:
            return 0
        try:
            arrays, _ = _decode_arrays_off(body[1:])
        except Exception:  # noqa: BLE001 - counting is best-effort
            return 0
        return sum(int(a.size) for a in arrays)

    @staticmethod
    def _trim_chunk(body, skip):
        """Drop up to ``skip`` leading tokens from one chunk frame
        (the dedup step of a resumed stream: the new leg replays from
        its snapshot position, which may trail what the client already
        received). Returns ``(new_body_or_None, dropped)``; None means
        the whole frame was already-delivered tokens on a non-terminal
        chunk — nothing to forward. A frame whose payload is not a
        token array passes through untouched."""
        status = body[0]
        try:
            arrays, _ = _decode_arrays_off(body[1:])
            arr = arrays[0]
        except Exception:  # noqa: BLE001 - not a token chunk
            return body, 0
        dropped = min(int(skip), int(arr.size))
        if dropped == 0:
            return body, 0
        arr = arr[dropped:]
        if arr.size == 0 and status == STATUS_STREAM:
            return None, dropped
        return struct.pack("<B", status) + encode_arrays([arr]), dropped

    # tpu-resource: acquires=router_socket releases=router_socket
    def _resume_leg(self, snap, fields, timeout, dead, phase=None,
                    max_attempts=None, tried=None):
        """Re-drive a broken decode stream from the held snapshot
        ``snap`` on each live replica not in ``dead``. On success
        returns ``(view, sock, first_body)`` with the registry
        in-flight slot for ``view.rid`` HELD by the caller; returns
        None when no candidate accepted. The forwarded marker
        ``fields`` ride along so the new leg keeps the original
        per-token budget, trace id, snapshot cadence — and, for the
        disaggregated decode leg, the REAL max-new-tokens that
        overrides the prefill snapshot's 1. A status-2 first frame is
        a refusal (identity skew or shed) and a status-1 frame a hard
        reject — both leave the socket at a frame boundary, so it is
        pooled and the next candidate tried.

        ``phase`` restricts candidates to one pool (the handoff's
        decode placement — free-slot-richest first), ``max_attempts``
        bounds distinct replicas tried this call, and ``tried`` (a
        set) records and excludes candidates ACROSS calls so the
        handoff's one retry provably lands on a different replica."""
        payload = snap + b"".join(
            struct.pack("<B", m) + raw for m, raw in fields)
        frame = build_request(CMD_KV_RESUME, payload)
        attempts = 0
        for v in self.registry.routable(phase):
            if v.rid in dead or (tried is not None and v.rid in tried):
                continue
            if max_attempts is not None and attempts >= max_attempts:
                break
            attempts += 1
            if tried is not None:
                tried.add(v.rid)
            self.registry.acquire(v.rid)
            sock = None
            try:
                sock = self._pool_get(v.rid)
                if sock is None:
                    sock = self._conn_open(v)
                sock.settimeout(timeout)
                sock.sendall(frame)
                (blen,) = struct.unpack("<I", _read_all(sock, 4))
                body = _read_all(sock, blen)
            except (OSError, ConnectionError):
                if sock is not None:
                    self._conn_close(sock)
                self.registry.report_io_error(v.rid)
                self._pool_drop(v.rid)
                self.registry.release(v.rid)
                continue
            if body and body[0] in (STATUS_STREAM, STATUS_OK):
                return v, sock, body
            self._pool_put(v.rid, sock)
            self.registry.release(v.rid)
        return None

    # tpu-resource: releases=router_socket
    def _relay(self, view, sock, first_body, client_conn, timeout,
               t_send, stream_ctx=None, init_snap=None, init_tokens=0,
               init_max_gap=0.0, owns_slot=False):
        """Pump chunk frames replica -> client until the terminal
        frame, surviving mid-stream replica death when a resume point
        is held. Owns ``sock`` (and every failover socket it dials)
        from here on: pools it on a clean terminal (the stream ends
        exactly at a frame boundary), poisons it on every other exit.
        ``t_send`` is when the request hit the replica's socket, so the
        FIRST gap really is time-to-first-token — the per-token SLO
        treats the first chunk as a token, and anchoring at relay
        start would hide exactly the slow-admission case the SLO
        exists to catch.

        With ``stream_ctx`` the replica leg was asked for kv-snapshot
        frames: the newest one is RETAINED (``_snap_hold`` /
        ``_snap_release``), and on a mid-stream replica death the
        stream is re-driven on another replica via the kv_resume
        command. Already-delivered tokens are trimmed by sequence
        position (never duplicated, never lost — a snapshot frame only
        arrives after every token it covers is on the wire, so the
        delivered count can never trail the held position), the
        inter-token gap clock keeps running across the outage (a
        failover does NOT refresh the last-frame timestamp or reset
        TTFT accounting — the client really did wait), and a client
        that never asked for snapshots sees byte-identical framing
        throughout because injected snapshot frames are stripped here.
        Without a held snapshot a death stays today's status-2
        terminal.

        The disaggregated decode leg enters here mid-stream:
        ``init_snap`` is the prefill handoff snapshot (re-held locally
        so this function's hold/release pairing stays self-contained),
        ``init_tokens`` tokens were already delivered by the prefill
        leg (the dedup arithmetic counts them), ``init_max_gap``
        carries the client's observed TTFT gap, and ``owns_slot=True``
        says ``view.rid``'s registry in-flight slot was acquired by
        ``_resume_leg`` and is ours to drop."""
        strip = bool(stream_ctx and stream_ctx.get("strip"))
        fields = [] if stream_ctx is None else stream_ctx["fields"]
        can_resume = stream_ctx is not None
        tokens = init_tokens
        max_gap = init_max_gap
        t_last = t_send
        rid = view.rid  # replica serving the CURRENT leg
        owned = owns_slot  # True while rid's in-flight slot is OURS
        skip = 0        # duplicate tokens still to trim on this leg
        dead = set()
        snap = None if init_snap is None else self._snap_hold(init_snap)

        def send(body):
            try:
                client_conn.sendall(struct.pack("<I", len(body)) + body)
            except (OSError, ConnectionError) as e:
                # the client vanished: close the REPLICA socket too
                # (never pooled — mid-stream), which makes the
                # replica's own send fail and purge the KV slot
                self._conn_close(sock)
                raise _ClientGone(str(e)) from e

        try:
            body = first_body
            while True:
                if (can_resume and body[0] == STATUS_STREAM
                        and is_kv_snapshot(body[1:])):
                    # a resume point, not tokens: retain the newest
                    if snap is not None:
                        self._snap_release(snap)
                    snap = self._snap_hold(body[1:])
                    if not strip:
                        # the client set its own cadence: it gets the
                        # frame verbatim AND the router still uses it
                        send(body)
                else:
                    if skip:
                        body, dropped = self._trim_chunk(body, skip)
                        skip -= dropped
                    if body is not None:
                        # duplicate-only frames are dropped above and
                        # deliberately do NOT touch the gap clock: the
                        # client is still waiting for its next NEW
                        # token, so the outage counts against the
                        # per-token budget
                        now = time.monotonic()
                        max_gap = max(max_gap, now - t_last)
                        t_last = now
                        tokens += self._chunk_tokens(body)
                        send(body)
                        if body[0] != STATUS_STREAM:
                            self._pool_put(rid, sock)
                            if rid != view.rid:
                                # the stream finished on a failover
                                # replica: report THAT one healthy (the
                                # original was already reported dead;
                                # replica_ok=False keeps the caller
                                # from overwriting that report)
                                self.registry.report_ok(rid)
                            return _Streamed(body[0], tokens, max_gap,
                                             replica_ok=rid == view.rid)
                try:
                    (blen,) = struct.unpack("<I", _read_all(sock, 4))
                    body = _read_all(sock, blen)
                except (OSError, ConnectionError):
                    # replica died mid-stream: the client already
                    # consumed a prefix, so no transparent re-send of
                    # the request — fail over from the held resume
                    # point, or terminate the stream retryably
                    self._conn_close(sock)
                    self.registry.report_io_error(rid)
                    self._pool_drop(rid)
                    dead.add(rid)
                    if owned:
                        self.registry.release(rid)
                        owned = False
                    t_died = time.monotonic()
                    nxt = None
                    if snap is not None:
                        nxt = self._resume_leg(snap, fields, timeout,
                                               dead)
                    if nxt is None:
                        _M_RESUMES.inc(
                            outcome="no_snapshot" if snap is None
                            else "refused")
                        send(struct.pack("<B", STATUS_OVERLOADED))
                        return _Streamed(STATUS_OVERLOADED, tokens,
                                         max_gap, replica_ok=False)
                    nview, sock, body = nxt
                    rid = nview.rid
                    owned = True
                    _M_RETRIES.inc(cause="stream_resume")
                    _M_RESUMES.inc(outcome="ok")
                    _M_RESUME_SECONDS.observe(
                        time.monotonic() - t_died)
                    hdr = decode_kv_snapshot_header(snap)
                    skip = max(0, tokens - int(hdr["n_generated"]))
        finally:
            if owned:
                self.registry.release(rid)
            if snap is not None:
                self._snap_release(snap)

    # ------------------------------------------------- disaggregation
    def _disagg_plan(self):
        """Placement decision for one genuine decode stream: ``None``
        = colocated (poolless fleet — every routable replica serves
        both phases), ``"handoff"`` = disaggregated prefill->decode
        handoff (both pure pools have a routable member), and
        ``"degraded"`` = the fleet IS pooled but a pure pool has
        nothing routable — serve colocated on whatever survives
        (counted + logged; recovers by itself once the missing pool
        scales back up or its replicas probe back in)."""
        views = self.registry.routable()
        if not any(v.phase != "both" for v in views):
            return None
        has_pre = any(v.phase == "prefill" for v in views)
        has_dec = any(v.phase == "decode" for v in views)
        return "handoff" if (has_pre and has_dec) else "degraded"

    @staticmethod
    def _handoff_frame(arrays_bytes, fwd_fields, tail):
        """The prefill leg's wire frame: the forwarded request with
        the handoff bit set on its decode field — the replica runs
        ONLY the prefill step and replies with one kv-snapshot frame
        then the terminal first-token frame."""
        out = []
        for m, raw in fwd_fields:
            if m == DECODE_MARKER:
                (val,) = struct.unpack("<Q", raw)
                raw = struct.pack("<Q", val | DECODE_HANDOFF_BIT)
            out.append((m, raw))
        body = arrays_bytes + b"".join(
            struct.pack("<B", m) + raw for m, raw in out) + tail
        return struct.pack("<I", len(body)) + body

    # tpu-resource: acquires=router_socket releases=router_socket
    def _prefill_leg(self, frame, timeout, deadline):
        """Run the prefill step of a disaggregated stream on the
        prefill pool (warm-bucket-first placement) and retry another
        prefill replica on death or refusal — the client has seen
        NOTHING yet, so a prefill replica SIGKILLed mid-handoff is
        invisible: the prefill re-runs elsewhere. Returns
        ``(view, raw_snap, term_body, t_send, retried)`` where
        ``raw_snap`` is the raw handoff-snapshot blob — NOT yet held;
        the caller takes ownership via ``_snap_hold`` — ``("error",
        body)`` for a genuine status-1 request error (forwarded to
        the client verbatim, never retried), or None when every
        prefill replica refused or failed."""
        attempts = 0
        retried = False
        for v in self.registry.routable("prefill"):
            if attempts >= self.retry_attempts:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            attempts += 1
            self.registry.acquire(v.rid)
            sock = None
            t_send = time.monotonic()
            try:
                sock = self._pool_get(v.rid)
                if sock is None:
                    sock = self._conn_open(v)
                sock.settimeout(timeout)
                sock.sendall(frame)
                (blen,) = struct.unpack("<I", _read_all(sock, 4))
                body = _read_all(sock, blen)
                term = None
                if body and body[0] == STATUS_STREAM \
                        and is_kv_snapshot(body[1:]):
                    (blen,) = struct.unpack("<I", _read_all(sock, 4))
                    term = _read_all(sock, blen)
            except (OSError, ConnectionError):
                if sock is not None:
                    self._conn_close(sock)
                self.registry.report_io_error(v.rid)
                self._pool_drop(v.rid)
                _M_RETRIES.inc(cause="handoff")
                retried = True
                continue
            finally:
                self.registry.release(v.rid)
            if term is not None and term[0] == STATUS_OK:
                self._pool_put(v.rid, sock)
                self.registry.report_ok(v.rid)
                return v, body[1:], term, t_send, retried
            if term is None and body and body[0] == STATUS_OVERLOADED:
                # refusal at a frame boundary: pool it, try the next
                self._pool_put(v.rid, sock)
                _M_RETRIES.inc(cause="handoff")
                retried = True
                continue
            if term is None and body and body[0] == STATUS_ERROR:
                # the REQUEST is bad, not the replica: no retry
                self._pool_put(v.rid, sock)
                return ("error", body)
            # surprise framing (version skew): poison, try another
            self._conn_close(sock)
            _M_RETRIES.inc(cause="handoff")
            retried = True
        return None

    # tpu-resource: acquires=kv_snapshot releases=kv_snapshot
    def _dispatch_handoff(self, arrays_bytes, fwd_fields, tail,
                          deadline, client_conn, stream_ctx, max_new):
        """Disaggregated dispatch of one decode stream (README
        "Disaggregated serving"): prefill leg on the prefill pool
        (handoff-bit cmd 1 -> kv snapshot + first token), the first
        token straight to the client (TTFT never waits for decode
        placement), then the decode leg seeds a decode replica via
        kv_resume — retried once on a DIFFERENT decode replica, then
        on any surviving replica (outcome ``degraded``) — and relays
        the rest with the full mid-stream resume machinery behind it.
        Returns a :class:`_Streamed` (the stream finished or ended
        with a retryable terminal — the client always sees
        ok-or-retryable, never a torn stream), a raw status-1 body
        (genuine request error from prefill, nothing relayed), or
        None (nothing reached the client and no prefill replica
        cooperated: the caller degrades to colocated dispatch)."""
        chaos.hit("fleet.handoff")
        timeout = min(self.handoff_timeout, self.backend_timeout)
        if deadline is not None:
            timeout = min(timeout,
                          max(0.05, deadline - time.monotonic()) + 1.0)
        pre = self._prefill_leg(
            self._handoff_frame(arrays_bytes, fwd_fields, tail),
            timeout, deadline)
        if pre is None:
            return None
        if pre[0] == "error":
            return pre[1]
        view, raw_snap, term, t_send, retried = pre
        snap = self._snap_hold(raw_snap)
        t_snap = time.monotonic()
        try:
            n_tok = self._chunk_tokens(term)
            if max_new <= n_tok:
                # the prefill token IS the whole stream (max_new 1):
                # forward the terminal verbatim, no decode leg at all
                try:
                    client_conn.sendall(
                        struct.pack("<I", len(term)) + term)
                except (OSError, ConnectionError) as e:
                    raise _ClientGone(str(e)) from e
                _M_HANDOFF.inc(
                    outcome="retried" if retried else "ok")
                return _Streamed(STATUS_OK, n_tok,
                                 time.monotonic() - t_send)
            # first token to the client NOW, as a stream chunk
            chunk = struct.pack("<B", STATUS_STREAM) + term[1:]
            try:
                client_conn.sendall(
                    struct.pack("<I", len(chunk)) + chunk)
            except (OSError, ConnectionError) as e:
                raise _ClientGone(str(e)) from e
            t_tok = time.monotonic()
            # decode placement: best decode replica, one retry on a
            # provably different one, then anywhere (degraded)
            tried = set()
            nxt = self._resume_leg(snap, fwd_fields, timeout, set(),
                                   phase="decode", max_attempts=1,
                                   tried=tried)
            outcome = "retried" if retried else "ok"
            if nxt is None and any(
                    v.rid not in tried
                    for v in self.registry.routable("decode")):
                _M_RETRIES.inc(cause="handoff")
                outcome = "retried"
                nxt = self._resume_leg(snap, fwd_fields, timeout,
                                       set(), phase="decode",
                                       max_attempts=1, tried=tried)
            if nxt is None:
                nxt = self._resume_leg(snap, fwd_fields, timeout,
                                       set(), tried=tried)
                if nxt is not None:
                    outcome = "degraded"
                    _LOG.warning(
                        "decode pool refused handoff: stream resumed "
                        "on %s (degraded to colocated)", nxt[0].rid)
            if nxt is None:
                # a token was already delivered, so this stream can
                # only END retryably — never silently torn
                _M_HANDOFF.inc(outcome="failed")
                try:
                    client_conn.sendall(struct.pack(
                        "<IB", 1, STATUS_OVERLOADED))
                except (OSError, ConnectionError) as e:
                    raise _ClientGone(str(e)) from e
                return _Streamed(STATUS_OVERLOADED, n_tok,
                                 t_tok - t_send, replica_ok=True)
            dview, dsock, dbody = nxt
            _M_HANDOFF.inc(outcome=outcome)
            _M_HANDOFF_SECONDS.observe(time.monotonic() - t_snap)
            # placement done: the relay reads at the normal per-reply
            # cap, not the short per-attempt handoff cap
            dsock.settimeout(self.backend_timeout)
            # ownership of the held snapshot transfers to _relay (it
            # re-holds init_snap on entry and releases on every exit
            # path) — our finally must not double-release it
            relay_snap, snap = snap, None
            streamed = self._relay(dview, dsock, dbody, client_conn,
                                   self.backend_timeout, t_tok,
                                   stream_ctx=stream_ctx,
                                   init_snap=relay_snap,
                                   init_tokens=n_tok,
                                   init_max_gap=t_tok - t_send,
                                   owns_slot=True)
            if streamed.replica_ok:
                self.registry.report_ok(dview.rid)
            return streamed
        finally:
            if snap is not None:
                self._snap_release(snap)

    def _forward_fresh(self, view, frame, timeout, client_conn=None,
                       stream_ctx=None):
        sock = self._conn_open(view)
        t_send = time.monotonic()
        try:
            sock.settimeout(timeout)
            sock.sendall(frame)
            (blen,) = struct.unpack("<I", _read_all(sock, 4))
            body = _read_all(sock, blen)
        except (OSError, ConnectionError):
            self._conn_close(sock)
            raise
        if body and body[0] == STATUS_STREAM:
            if client_conn is not None:
                return self._relay(view, sock, body, client_conn, timeout,
                                   t_send, stream_ctx)
            # same version-skew poison as _forward: mid-stream sockets
            # never reach the pool
            self._conn_close(sock)
            return body
        self._pool_put(view.rid, sock)
        return body

    # ------------------------------------------------------------ routing
    def _route_once(self, tried):
        """Pick the next replica: least-loaded routable one not yet
        tried this request; falls back to an already-tried one (it may
        have shed transiently) rather than giving up while anything is
        routable. Returns a ReplicaView or None."""
        chaos.hit("fleet.route")
        routable = self.registry.routable()
        for view in routable:
            if view.rid not in tried:
                return view
        return routable[0] if routable else None

    def _dispatch(self, arrays_bytes, fields, tail, deadline,
                  stream=False, client_conn=None):
        """Route one admitted cmd-1 request with shed-aware retry.
        Returns the raw response body to send to the client — or a
        :class:`_Streamed` summary when the reply was a chunk stream
        already relayed to ``client_conn`` (streaming retries happen
        only BEFORE the first relayed frame: an immediate status-2
        shed re-routes exactly like a one-shot request, but once the
        client consumed a chunk the stream ends retryably instead).
        Never raises for fleet-topology failures — those become
        status 2 (except :class:`_ClientGone`: nobody left to tell)."""
        # forward everything except the tenant field (admission
        # happened here; replicas predating the field would stop
        # parsing at it and miss a deadline/trace field behind it).
        # For a relayed stream with router-managed resume enabled, the
        # forwarded decode field additionally gets the router's
        # snapshot cadence stamped into its spare bits when the client
        # set none — the replica then interleaves resume points that
        # the relay strips before the client (byte-identical framing
        # for clients that never opted in) and uses for failover. A
        # client that set its OWN cadence keeps it; its snapshot
        # frames are forwarded verbatim AND double as the router's
        # resume points.
        fwd_fields = []
        strip_snaps = False
        client_cadence = 0
        decode_val = 0
        for m, raw in fields:
            if m == TENANT_MARKER:
                continue
            if m == DECODE_MARKER and stream:
                (val,) = struct.unpack("<Q", raw)
                decode_val = val
                client_cadence = ((val >> DECODE_SNAPSHOT_EVERY_SHIFT)
                                  & DECODE_SNAPSHOT_EVERY_MASK)
                if not client_cadence and self.snapshot_every:
                    val |= (self.snapshot_every
                            << DECODE_SNAPSHOT_EVERY_SHIFT)
                    raw = struct.pack("<Q", val)
                    strip_snaps = True
            fwd_fields.append((m, raw))
        fwd_body = arrays_bytes + b"".join(
            struct.pack("<B", m) + raw for m, raw in fwd_fields) + tail
        frame = struct.pack("<I", len(fwd_body)) + fwd_body
        stream_ctx = None
        if stream and (strip_snaps or client_cadence):
            stream_ctx = {"fields": fwd_fields, "strip": strip_snaps}
        if stream_ctx is not None and client_conn is not None:
            # phase-pooled fleet: serve genuine streams as a
            # prefill->decode handoff; degrade to plain colocated
            # dispatch (below) when a pure pool has nothing routable
            # or no prefill replica cooperated
            plan = self._disagg_plan()
            if plan is not None:
                reason = "pool_empty"
                if plan == "handoff":
                    max_new = int(decode_val & 0xFFFFFFFF) or 64
                    resp = self._dispatch_handoff(
                        arrays_bytes, fwd_fields, tail, deadline,
                        client_conn, stream_ctx, max_new)
                    if resp is not None:
                        return resp
                    reason = "no_prefill_placement"
                _M_HANDOFF.inc(outcome="degraded")
                _LOG.warning(
                    "disaggregated serving degraded to colocated "
                    "(%s)", reason)
        delays = backoff_delays(self.retry_attempts, self.retry_base,
                                self.retry_max, 0.5, self._rng)
        tried = set()
        last_shed = None
        for attempt in range(1, self.retry_attempts + 1):
            if deadline is not None and time.monotonic() >= deadline:
                raise ShedError("deadline")
            view = self._route_once(tried)
            if view is None:
                raise ShedError("no_replica")
            tried.add(view.rid)
            timeout = self.backend_timeout
            if deadline is not None:
                timeout = min(timeout,
                              max(0.05, deadline - time.monotonic()) + 1.0)
            self.registry.acquire(view.rid)
            try:
                resp = self._forward(
                    view, frame, timeout,
                    client_conn=client_conn if stream else None,
                    stream_ctx=stream_ctx)
            except _ClientGone:
                raise
            except (OSError, ConnectionError):
                # dead / stalled replica: poison it and fail over to a
                # different one immediately — detection, not load
                self.registry.report_io_error(view.rid)
                self._pool_drop(view.rid)
                _M_RETRIES.inc(cause="io")
                continue
            finally:
                self.registry.release(view.rid)
            if isinstance(resp, _Streamed):
                # frames already went to the client; a mid-relay
                # replica death was reported inside the relay and must
                # not be overwritten by an ok report here
                if resp.replica_ok:
                    self.registry.report_ok(view.rid)
                return resp
            self.registry.report_ok(view.rid)
            if resp and resp[0] == STATUS_OVERLOADED:
                last_shed = resp
                if attempt == self.retry_attempts:
                    break
                delay = next(delays)
                if deadline is not None and \
                        time.monotonic() + delay >= deadline:
                    raise ShedError("deadline")
                _M_RETRIES.inc(cause="shed")
                time.sleep(delay)
                continue
            return resp
        if last_shed is not None:
            return last_shed  # retries exhausted: the shed stands
        raise ShedError("retries_exhausted")

    def _infer(self, body, client_conn=None):
        """Admission + dispatch + accounting for one cmd-1 request.
        Returns the response body bytes — or None when the reply was a
        chunk stream already relayed to ``client_conn``."""
        t0 = time.perf_counter()
        arrays_bytes, fields, tail, tid, budget, _trace = \
            _split_meta(body)
        decode_val = next((struct.unpack("<Q", raw)[0]
                           for m, raw in fields if m == DECODE_MARKER),
                          None)
        oneshot = (decode_val is not None
                   and bool(decode_val & DECODE_ONESHOT_BIT))
        # only a chunk-relay dispatch for genuine streams: a one-shot
        # decode is a normal single reply with normal retry semantics
        stream = decode_val is not None and not oneshot
        budget_total = budget
        if budget is not None and decode_val is not None:
            # for decode requests the 0xDD field is a PER-TOKEN budget
            # (TTFT + every inter-token gap), not an end-to-end
            # deadline: the router's whole-request bound scales by the
            # token count (+1 for the first token), or a legitimate
            # 64-token one-shot reply would blow a 500ms per-token
            # budget, time out the read, and eject the healthy replica
            # that was busy completing it
            max_new = int(decode_val & 0xFFFFFFFF) or 64
            budget_total = budget * (max_new + 1)
        deadline = (None if budget_total is None
                    else time.monotonic() + budget_total)
        # the SLO used for deadline-hit accounting: per-token for a
        # stream (checked against the max inter-chunk gap), whole-reply
        # for everything else; fall back to the tenant policy's slo_ms
        slo_s = budget if stream else budget_total
        if slo_s is None:
            slo_ms = self.gate._state_for(tid).policy.slo_ms
            slo_s = None if slo_ms is None else slo_ms / 1000.0
        tenant_name = None
        outcome = "error"
        status = STATUS_ERROR
        tokens = 0
        try:
            admit_timeout = (budget_total if budget_total is not None
                             else self.admit_timeout)
            try:
                tenant_name = self.gate.acquire(tid, admit_timeout)
            except ShedError as e:
                tenant_name = tenant_name or self._tenant_name(tid)
                _M_SHEDS.inc(tenant=tenant_name, reason=e.reason)
                outcome = "shed"
                status = STATUS_OVERLOADED
                return struct.pack("<B", STATUS_OVERLOADED)
            try:
                resp = self._dispatch(arrays_bytes, fields, tail,
                                      deadline, stream=stream,
                                      client_conn=client_conn)
            except ShedError as e:
                _M_SHEDS.inc(tenant=tenant_name, reason=e.reason)
                outcome = "shed"
                status = STATUS_OVERLOADED
                return struct.pack("<B", STATUS_OVERLOADED)
            except _ClientGone:
                # the client vanished mid-relay: nobody to answer,
                # accounted as a shed (the fleet did not fail)
                outcome = "shed"
                status = STATUS_OVERLOADED
                raise
            except Exception:  # noqa: BLE001 — router fault, not the
                # request's fault: the contract is ok-or-retryable, so
                # an internal routing failure (including an armed
                # chaos fault on fleet.route) sheds instead of erroring
                _M_SHEDS.inc(tenant=tenant_name, reason="router_fault")
                outcome = "shed"
                status = STATUS_OVERLOADED
                return struct.pack("<B", STATUS_OVERLOADED)
            finally:
                self.gate.release()
            if isinstance(resp, _Streamed):
                # chunk stream, already relayed: per-token SLO — the
                # request is "late" when any inter-chunk gap (incl.
                # time to the first chunk) blew the budget
                status = resp.status
                tokens = resp.tokens
                if status == STATUS_OK:
                    met = slo_s is None or resp.max_gap_s <= slo_s
                    outcome = "ok" if met else "late"
                elif status == STATUS_OVERLOADED:
                    outcome = "shed"
                else:
                    outcome = "error"
                return None
            status = resp[0] if resp else STATUS_ERROR
            if status == STATUS_OK:
                met = (slo_s is None
                       or time.perf_counter() - t0 <= slo_s)
                outcome = "ok" if met else "late"
            elif status == STATUS_OVERLOADED:
                outcome = "shed"
            else:
                outcome = "error"
            return resp
        finally:
            name = tenant_name or self._tenant_name(tid)
            dt = time.perf_counter() - t0
            _M_REQUESTS.inc(tenant=name, status=str(status))
            if slo_s is not None:
                # every request of an SLO-carrying tenant is a hit or
                # a miss — a shed/error against a deadline is a miss
                _M_DEADLINE.inc(tenant=name,
                                outcome="hit" if outcome == "ok"
                                else "miss")
            obs_goodput.SERVING_LEDGER.record(name, outcome, dt,
                                              tokens=tokens)

    def _tenant_name(self, tid):
        return self.gate._state_for(tid).policy.name

    # ------------------------------------------------------------- drains
    def drain(self, rid, deadline_s=10.0, notify_replica=True):
        """Zero-drop drain of one replica: stop routing new work to it,
        tell the replica itself (wire cmd 8) so its own health
        announces the drain, then wait until the router's in-flight
        count on it reaches zero. Returns True when drained, False on
        timeout (in-flight work still running — the caller decides
        whether to stop anyway)."""
        self.registry.set_draining(rid, True)
        if notify_replica:
            ep = self.registry.endpoints().get(rid)
            if ep is not None:
                try:
                    with socket.create_connection(
                            ep, timeout=self.registry.dial_timeout) as s:
                        s.settimeout(self.registry.dial_timeout)
                        payload = struct.pack("<Bd", CMD_DRAIN, float(deadline_s))
                        s.sendall(struct.pack("<I", len(payload)) + payload)
                        (blen,) = struct.unpack("<I", _read_all(s, 4))
                        _read_all(s, blen)
                except (OSError, ConnectionError):
                    pass  # dead replica drains trivially
        t_end = time.monotonic() + max(0.0, deadline_s)
        while time.monotonic() < t_end:
            if self.registry.inflight(rid) == 0:
                return True
            time.sleep(0.01)
        return self.registry.inflight(rid) == 0

    def undrain(self, rid, notify_replica=True):
        """Re-admit a drained replica for routing (after a reload
        finished, say)."""
        if notify_replica:
            ep = self.registry.endpoints().get(rid)
            if ep is not None:
                try:
                    with socket.create_connection(
                            ep, timeout=self.registry.dial_timeout) as s:
                        s.settimeout(self.registry.dial_timeout)
                        payload = struct.pack("<Bd", CMD_DRAIN, -1.0)
                        s.sendall(struct.pack("<I", len(payload)) + payload)
                        (blen,) = struct.unpack("<I", _read_all(s, 4))
                        _read_all(s, blen)
                except (OSError, ConnectionError):
                    pass
        self.registry.set_draining(rid, False)

    # ------------------------------------------------------------- server
    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            with self._conns_lock:
                self._conns[t] = conn
            t.start()

    def _handle(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                conn.settimeout(None)
                first = conn.recv(1)
                if not first:
                    raise ConnectionError("peer closed")
                conn.settimeout(self.backend_timeout)
                (blen,) = struct.unpack("<I", first + _read_all(conn, 3))
                if blen == 0:
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    continue
                try:
                    body = _read_all(conn, blen, limit=self.max_body)
                except BodyTooLarge:
                    # same hardening as the replica server: a bogus
                    # length prefix must not buffer gigabytes on the
                    # front tier; the stream can't be resynced — error
                    # status, then close
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    return
                cmd = body[0]
                if cmd == CMD_STOP:
                    conn.sendall(struct.pack("<IB", 1, STATUS_OK))
                    threading.Thread(target=self.stop,
                                     daemon=True).start()
                    return
                if cmd == CMD_HEALTH:
                    enc = json.dumps(self.health()).encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    continue
                if cmd == CMD_STATS:
                    enc = json.dumps(self.stats()).encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    continue
                if cmd == CMD_METRICS:
                    enc = obs_prometheus.render().encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    continue
                if cmd != CMD_INFER:
                    # reload/stop of individual replicas goes through
                    # Fleet.rolling_reload — a router-wide cmd 4 would
                    # be ambiguous about which replica it names
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    continue
                try:
                    resp = self._infer(body, client_conn=conn)
                    if resp is not None:
                        conn.sendall(struct.pack("<I", len(resp)) + resp)
                    # resp None: chunk stream already relayed
                except _ClientGone:
                    raise ConnectionError("client gone mid-stream")
                except Exception:  # noqa: BLE001 - wire error status
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
        except socket.timeout:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                self._conns.pop(threading.current_thread(), None)

    # -------------------------------------------------------------- views
    def health(self):
        """Fleet-level health JSON (wire cmd 3 on the router): replica
        table with states, plus the gate view. ``ok`` is true while at
        least one replica is routable."""
        replicas = [v.as_dict() for v in self.registry.snapshot()]
        routable = sum(1 for r in replicas if r["state"] == "ok")
        pools = {}
        for r in replicas:
            ph = r.get("phase") or "both"
            pools[ph] = pools.get(ph, 0) + 1
        return {
            "ok": routable > 0 and not self._stop.is_set(),
            "router": True,
            "draining": self._stop.is_set(),
            "accepting": not self._stop.is_set(),
            "routable_replicas": routable,
            "replicas": replicas,
            "pools": pools,
            "tenants": self.gate.stats(),
        }

    def stats(self):
        replicas = [v.as_dict() for v in self.registry.snapshot()]
        pools = {}
        for r in replicas:
            ph = r.get("phase") or "both"
            pools[ph] = pools.get(ph, 0) + 1
        return {
            "router": True,
            "port": self.port,
            "retry_attempts": self.retry_attempts,
            "max_inflight": self.gate.capacity,
            "tenants": self.gate.stats(),
            "replicas": replicas,
            "pools": pools,
            "serving_goodput": obs_goodput.SERVING_LEDGER.report(),
        }

    # -------------------------------------------------------------- close
    # tpu-resource: releases=router_socket
    def stop(self):
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # quiesce in-flight handler threads: their finally blocks
        # release every held kv_snapshot and backend socket, so
        # stop() returning means the resource census has drained.
        # Bounded — a handler wedged in a backend read must not hang
        # shutdown (its daemon thread dies with the process).
        with self._conns_lock:
            handlers = list(self._conns.keys())
        deadline = time.monotonic() + 5.0
        me = threading.current_thread()
        for t in handlers:
            if t is me:
                continue
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._pools_lock:
            pools = list(self._pools.values())
            self._pools = {}
        for pool in pools:
            for s in pool:
                try:
                    s.close()
                except OSError:
                    pass
        if self._own_registry:
            self.registry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
