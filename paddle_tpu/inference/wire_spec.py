"""wire_spec — the single machine-readable source of truth for the
paddle_tpu serving wire protocol.

Every constant of the protocol lives HERE and nowhere else: the Python
server/router/decode stack imports it, the Go/R/C clients mirror it,
and the TPU401–TPU410 protocol lint family
(``paddle_tpu/analysis/protocol.py``, surfaced as
``tools/tracelint.py --protocol`` and the strict
``tools/ci_gate.py --protocol`` stage) extracts each implementation's
constant tables and diffs them against this module — so the protocol
can never again drift one language at a time (the i64→f32 silent-cast
bug and the truncated-but-ok streaming hazard were both exactly that
kind of drift).

This module is deliberately self-contained (stdlib + numpy only, no
paddle_tpu imports) so the analyzer and external tooling can load it
standalone — ``from paddle_tpu.inference.wire_spec import ...`` is the
compatibility reference for duck-typed or out-of-tree clients (see
MIGRATION.md "Wire-protocol spec module").

Framing (little-endian throughout)
----------------------------------

    request:  u32 body_len | u8 cmd | payload
    response: u32 body_len | u8 status | payload

A cmd-1 infer payload is ``u8 n_inputs`` followed by one array block
per input::

    u8 dtype_code | u8 ndim | i64 dims[ndim] | data (row-major)

optionally followed by trailing marker-tagged fields, each exactly
9 bytes (``u8 marker | 8-byte payload``), in any order, each marker at
most once. Parsing stops at the first unknown marker: old servers
ignored trailing garbage, and a field a server predates must not be
misread.

Streaming decode replies (requests carrying the 0x5C field without its
one-shot bit): zero or more frames with status 3 — one token-array
chunk each, echoing the prompt's dtype — terminated by exactly ONE
frame with a terminal status (0 final chunk / 1 error / 2 retryable).
Only a client that sent 0x5C without bit 63 ever sees status 3, and a
broken connection mid-stream is always surfaced retryable, never as a
silent clean end.

KV snapshots (stream resume & prefill→decode handoff)
-----------------------------------------------------

A *kv-snapshot block* is a self-describing serialization of one live
decode sequence::

    u8 KV_FRAME_MAGIC | u16 version | u32 header_len |
    UTF-8 JSON header | array block (same per-array encoding as infer)

The JSON header carries the replica-identity fields (model
fingerprint, weights digest, quant mode, mesh descriptor) plus the
greedy-state scalars
(pos, last_token, n_generated, ...); the array block is
``[prompt, generated-token tail, per-layer KV pages]`` reusing the
dtype table above. A replica whose identity skews from the header
refuses the snapshot with status 2 — never silent wrong tokens.

When a streaming request sets the cadence bits of the 0x5C field, the
reply stream interleaves status-3 *snapshot frames* (payload = one
kv-snapshot block, first byte ``KV_FRAME_MAGIC``) between the ordinary
token chunks. A token chunk's first payload byte is the u8 array count
(always small), so the magic byte disambiguates unambiguously. Clients
that never set the cadence bits never see a snapshot frame — the fleet
router sets them on the replica leg and strips the snapshot frames
before forwarding, so client-visible bytes are unchanged.

Error taxonomy (the ok-or-retryable contract)
---------------------------------------------

Every request ends with status 0 (correct tensors) or status 2
(retryable) under any single-component failure; status 1 is reserved
for genuine request errors (bad dtype/shape, permanent misuse). The
taxonomy below classifies every exception class the Python serving
stack raises; the protocol lint statically verifies that retryable
classes only ever map to wire status 2, permanent classes to status 1,
and that no unclassified exception can escape a handler into a hang.
"""
import json
import struct
from collections import namedtuple

import numpy as np

#: Bump on any change to the spec tables below — extracted by the
#: protocol lint and recorded in its reports.
SPEC_VERSION = 2

# --------------------------------------------------------------- dtypes

WireDtype = namedtuple("WireDtype", "code name size np_name")

#: The wire dtype table. ``code`` is the on-wire u8, ``size`` the
#: element size in bytes, ``np_name`` the numpy dtype the Python side
#: materialises. Mirrored by: Go ``dtypeF32..`` consts + ``dtypeSize``
#: map, R ``.pd_dtype_codes`` / ``.pd_dtype_sizes``, C ``dtype_size()``.
DTYPES = {
    0: WireDtype(0, "float32", 4, "float32"),
    1: WireDtype(1, "int32", 4, "int32"),
    2: WireDtype(2, "int64", 8, "int64"),
    3: WireDtype(3, "bool", 1, "bool"),
}

DTYPE_BY_NAME = {d.name: d for d in DTYPES.values()}

#: Highest valid dtype code (clients reject anything above — a newer
#: server must never be "guessed at").
MAX_DTYPE_CODE = max(DTYPES)

#: numpy dtype objects by wire code (the server's decode table).
NUMPY_BY_CODE = {c: np.dtype(d.np_name) for c, d in DTYPES.items()}

#: wire code by numpy dtype (the server's encode table).
CODE_BY_NUMPY = {np.dtype(d.np_name): c for c, d in DTYPES.items()}

#: Wire dtype codes valid as decode prompts / token ids (input array 0
#: of a 0x5C-tagged request; the streamed token chunks echo the
#: prompt's dtype).
TOKEN_DTYPE_CODES = frozenset({DTYPE_BY_NAME["int32"].code,
                               DTYPE_BY_NAME["int64"].code})

#: Exact widenings only: these encode as f32 without corruption.
#: Anything else (f64, unsigned, complex, ...) must RAISE, never
#: silently cast — the pre-PR-4 behaviour corrupted i64 token ids
#: through an f32 cast.
WIDEN_TO_F32 = frozenset({"float16", "bfloat16"})

# ------------------------------------------------------------- statuses

WireStatus = namedtuple("WireStatus", "code name terminal doc")

#: Reply status bytes. ``terminal`` is False only for the stream-chunk
#: status: a streaming reply is 0+ status-3 frames then exactly one
#: terminal frame.
STATUSES = {
    0: WireStatus(0, "ok", True,
                  "success; cmd-1 replies carry the output arrays "
                  "(for a stream: the final chunk, possibly empty)"),
    1: WireStatus(1, "error", True,
                  "permanent request error (bad dtype/shape/command); "
                  "retrying the same request cannot succeed"),
    2: WireStatus(2, "retryable", True,
                  "transient: shed by the bounded queue, quarantined "
                  "bucket, scheduler restart, expired deadline, or a "
                  "fleet-topology fault — back off and retry"),
    3: WireStatus(3, "stream", False,
                  "non-final chunk of a streaming decode reply (one "
                  "token array, or a kv-snapshot frame when the "
                  "request set the cadence bits; never sent unless "
                  "the request carried the 0x5C field without its "
                  "one-shot bit)"),
}

STATUS_OK = 0
STATUS_ERROR = 1
STATUS_RETRYABLE = 2
STATUS_STREAM = 3

#: Statuses the server can emit on the wire. A client branch handling
#: any byte OUTSIDE this set decodes a status that can never arrive —
#: dead protocol surface the lint flags as drift.
SERVER_EMITTED_STATUSES = frozenset(STATUSES)

# ------------------------------------------------------------- commands

WireCommand = namedtuple("WireCommand", "code name request response doc")

#: Request command bytes and their frame grammar (payload = the bytes
#: after the cmd byte).
COMMANDS = {
    1: WireCommand(
        1, "infer",
        "u8 n_inputs | per input: u8 dtype u8 ndim i64 dims[] data | "
        "optional 9-byte marker fields, any order",
        "status + same per-array encoding of the outputs (streaming "
        "decode: status-3 chunk frames then one terminal frame)",
        "run the model (through the batching engine when attached; "
        "0x5C-tagged bodies route to the continuous-batching decode "
        "engine)"),
    3: WireCommand(
        3, "health", "(empty)",
        "status 0 + UTF-8 JSON liveness/readiness body (its `phase` "
        "key declares the replica's pool: prefill | decode | both; "
        "absent means both)",
        "liveness + readiness probe (accepting / draining_deadline_s "
        "announce drains; absent fields mean accepting; `phase` drives "
        "the router's disaggregated prefill/decode placement)"),
    4: WireCommand(
        4, "reload", "optional UTF-8 model prefix (empty = same)",
        "status 0 + UTF-8 JSON, or status 1 + error text",
        "hot model reload: load + warm off to the side, atomic swap, "
        "drain the old engine — zero drops, zero post-swap cold "
        "compiles (serve_model servers only; the router refuses it)"),
    5: WireCommand(
        5, "stats", "(empty)",
        "status 0 + UTF-8 JSON engine counters (decode engines echo "
        "their `phase` alongside the counters)",
        "batching/decode engine counters (per-bucket compiles/hits/"
        "latency, breaker states, queue depth, shed counts)"),
    6: WireCommand(
        6, "metrics", "(empty)",
        "status 0 + Prometheus text exposition 0.0.4",
        "process obs registry exposition (the wire twin of the "
        "serve_model(metrics_port=...) HTTP endpoint)"),
    7: WireCommand(
        7, "stop", "(empty)", "status 0 (ack, then graceful drain)",
        "graceful shutdown: drain in-flight work, close"),
    8: WireCommand(
        8, "drain", "optional f64 drain budget seconds (< 0 = undrain)",
        "status 0 + health JSON",
        "drain announce: health flips accepting=false so routers stop "
        "sending new work, but everything that arrives still serves"),
    9: WireCommand(
        9, "kv_put",
        "one kv-snapshot block (magic, version, JSON header, arrays)",
        "status 0 + UTF-8 JSON echo of the accepted header; status 2 "
        "when the snapshot does not match this replica's identity "
        "(fingerprint/quant/mesh skew); status 1 on a malformed block",
        "validate a KV snapshot against this replica — the stateless "
        "preflight of the resume/handoff flow (the prefill-to-decode "
        "handoff rides the same block format)"),
    10: WireCommand(
        10, "kv_resume",
        "one kv-snapshot block, then optional 9-byte marker fields, "
        "any order (per-token budget, trace id, decode opts/cadence)",
        "streaming decode grammar: status-3 chunk frames carrying the "
        "tokens AFTER the snapshot position, then one terminal frame; "
        "an identity-skewed replica refuses with status 2 before any "
        "chunk",
        "resume a decode stream from a snapshot at its exact sequence "
        "position; the resumed suffix is bitwise identical to an "
        "unbroken solo decode (greedy state is RNG-free)"),
}

CMD_INFER = 1
CMD_HEALTH = 3
CMD_RELOAD = 4
CMD_STATS = 5
CMD_METRICS = 6
CMD_STOP = 7
CMD_DRAIN = 8
CMD_KV_PUT = 9
CMD_KV_RESUME = 10

# -------------------------------------------------- trailing marker fields

WireMarker = namedtuple("WireMarker", "byte name fmt doc")

#: Optional trailing fields on cmd-1 infer bodies. A marker byte (not
#: bare trailing bytes) so garbage tails can't be misread as a field;
#: each field is exactly ``u8 marker + 8 payload bytes``; fields may
#: appear in any order, each marker at most once; parsing stops at the
#: first unknown marker.
MARKERS = {
    0xDD: WireMarker(0xDD, "deadline", "<d",
                     "f64 relative budget in ms; the server computes "
                     "the absolute deadline at receipt and drops the "
                     "request without dispatch once it expires (decode "
                     "requests: the PER-TOKEN budget — TTFT and every "
                     "inter-token gap)"),
    0x1D: WireMarker(0x1D, "trace", "<Q",
                     "u64 non-zero trace id tagging the request's "
                     "obs.tracing spans (enqueue/batch/execute/reply)"),
    0x7E: WireMarker(0x7E, "tenant", "<Q",
                     "u64 tenant id (fleet.tenant_id(name)); the fleet "
                     "router keys WFQ admission and per-tenant SLO "
                     "accounting on it; a direct replica parses and "
                     "ignores it"),
    0x5C: WireMarker(0x5C, "decode", "<Q",
                     "u64 decode opts: low 32 bits max_new_tokens, "
                     "bits 32-47 snapshot cadence (emit a kv-snapshot "
                     "frame every N generated tokens; 0 = never), "
                     "bit 61 speculative decode opt-in (the engine may "
                     "draft-and-verify k tokens per iteration; emitted "
                     "tokens stay bitwise-equal to non-speculative "
                     "greedy, only chunk cadence may change — clients "
                     "that do not set the bit see byte-identical "
                     "streams), bit 62 prefill-handoff (run ONLY the "
                     "prefill step and reply with one status-3 "
                     "kv-snapshot frame then the terminal token frame "
                     "— the router's disaggregated prefill leg), bit "
                     "63 one-shot (collect the whole sequence into a "
                     "single reply instead of a chunk stream)"),
}

MARKER_BY_NAME = {m.name: m for m in MARKERS.values()}

DEADLINE_MARKER = 0xDD
TRACE_MARKER = 0x1D
TENANT_MARKER = 0x7E
DECODE_MARKER = 0x5C

#: Bit 63 of the decode field's u64: one-shot single reply.
DECODE_ONESHOT_BIT_SHIFT = 63
DECODE_ONESHOT_BIT = 1 << DECODE_ONESHOT_BIT_SHIFT

#: Bits 32-47 of the decode field's u64: snapshot cadence (emit a
#: kv-snapshot frame every N generated tokens; 0 disables).
DECODE_SNAPSHOT_EVERY_SHIFT = 32
DECODE_SNAPSHOT_EVERY_MASK = 0xFFFF

#: Bit 62 of the decode field's u64: prefill handoff. The server runs
#: ONLY the prefill step (max_new_tokens is forced to 1) and replies
#: deterministically with exactly two frames: one status-3 kv-snapshot
#: frame at n_generated=1, then the terminal status-0 frame carrying
#: the first token. The fleet router's disaggregated prefill leg — a
#: snapshot handed to a decode replica over kv_put/kv_resume continues
#: the stream bitwise-identically to colocated serving.
DECODE_HANDOFF_BIT_SHIFT = 62
DECODE_HANDOFF_BIT = 1 << DECODE_HANDOFF_BIT_SHIFT

#: Bit 61 of the decode field's u64: speculative-decode opt-in. The
#: engine may run a draft model ahead and verify k tokens per
#: iteration in one batched program; greedy accept/reject keeps the
#: emitted tokens bitwise-equal to non-speculative greedy decode, so
#: the only observable change is chunk cadence (several tokens may
#: land in one status-3 frame). Requests WITHOUT the bit decode
#: non-speculatively and their byte streams are identical to a
#: pre-speculation server's — cadence bits only, never content.
DECODE_SPEC_BIT_SHIFT = 61
DECODE_SPEC_BIT = 1 << DECODE_SPEC_BIT_SHIFT

#: Replica phases a server may declare in its cmd-3 health body (and
#: echo in cmd-5 stats): a `prefill` replica is placed for prompt
#: ingestion (large prompt buckets), a `decode` replica for token
#: generation (many KV slots), `both` serves colocated. Phase is a
#: PLACEMENT attribute: every phase still serves every command, so a
#: fleet whose other pool collapsed can degrade to colocated serving
#: on the survivors instead of failing requests.
REPLICA_PHASES = ("prefill", "decode", "both")

#: First payload byte of a kv-snapshot block (and of the status-3
#: snapshot frames that carry one). A token chunk's first payload byte
#: is its u8 array count, far below this value, so the two frame
#: payloads can never be confused.
KV_FRAME_MAGIC = 0xA7

#: Version of the kv-snapshot block layout + JSON header schema.
KV_SNAPSHOT_VERSION = 1

#: JSON-header keys every kv-snapshot block must carry. Identity keys
#: (fingerprint/weights/quant/mesh) gate resume: a mismatch is a
#: refusal (status 2), never silent wrong tokens. ``fingerprint`` is
#: the *program* identity (location-free module hash — weights are
#: runtime arguments and deliberately absent from it), so ``weights``
#: carries the parameter-value digest separately: two replicas with
#: the same architecture but different weights must refuse each
#: other's snapshots.
KV_HEADER_REQUIRED = ("v", "fingerprint", "weights", "quant", "mesh",
                      "pos", "last_token", "n_generated", "prompt_len")

#: Total wire size of one marker field (marker byte + 8 payload bytes).
FIELD_SIZE = 9

# ------------------------------------------------------- error taxonomy

#: Exception classes (by name — the protocol lint is static) that mean
#: "transient, retry": the server maps every one of them to wire
#: status 2, NEVER to status 1 and never to a hang. ``EngineClosed``
#: rides along: a request racing a hot reload/stop lands on the
#: swapped-in engine or a restarted server on its next attempt.
RETRYABLE_EXCEPTIONS = frozenset({
    "RetryableError",      # inference.batching — the base class
    "EngineOverloaded",    # bounded queue full: load shed
    "SchedulerRestarted",  # watchdog restarted a dead/wedged scheduler
    "BucketQuarantined",   # circuit breaker open for this bucket
    "DeadlineExceeded",    # dropped before dispatch, no compute spent
    "EngineClosed",        # raced a reload/stop; next attempt lands
    "ShedError",           # router-side shed (queue/deadline/replicas)
    "TimeoutError",        # an engine reply overran its bound
    "SnapshotRefused",     # kv snapshot skewed from replica identity:
                           # resume elsewhere; never silent wrong tokens
})

#: Exception classes that mean "the request itself is wrong": mapped to
#: wire status 1; retrying the same bytes cannot succeed.
PERMANENT_EXCEPTIONS = frozenset({
    "ValueError", "TypeError", "KeyError", "NotImplementedError",
    "RuntimeError",    # misuse (reload without loader, closed server)
    "BodyTooLarge",    # frame cap exceeded: status 1, then close
})

#: Exception classes owned by the transport or handler-internal control
#: flow: there is nobody to answer (the peer is gone) or the frame
#: stream cannot be resynced — these never map to a wire status.
TRANSPORT_EXCEPTIONS = frozenset({
    "ConnectionError", "BrokenPipeError", "ConnectionResetError",
    "OSError", "InterruptedError", "TimeoutExpired",
    "_ClientGone",     # router: the CLIENT vanished mid-relay
    "socket.timeout", "timeout",
})


def classify_exception(name):
    """'retryable' | 'permanent' | 'transport' | None for an exception
    class name (unqualified, as it appears at the raise site)."""
    if name in RETRYABLE_EXCEPTIONS:
        return "retryable"
    if name in PERMANENT_EXCEPTIONS:
        return "permanent"
    if name in TRANSPORT_EXCEPTIONS:
        return "transport"
    return None


def status_for_exception(name):
    """The wire status an exception class must map to (None when it
    never crosses the wire)."""
    kind = classify_exception(name)
    if kind == "retryable":
        return STATUS_RETRYABLE
    if kind == "permanent":
        return STATUS_ERROR
    return None


# ------------------------------------------- implementation declarations

Implementation = namedtuple(
    "Implementation", "name lang path commands markers statuses dtypes "
                      "streaming partial")

#: The four protocol implementations and the slice of the spec each one
#: declares. The protocol lint fails on any constant an implementation
#: defines at a value differing from the spec, on any spec feature the
#: declaration claims that the code does not actually implement, and on
#: any status/dtype a client decodes that the server never emits.
#: ``partial`` documents intentional gaps (MIGRATION.md "waiver tag"):
#: a feature absent from BOTH the declaration and the code is a
#: documented partial client, not drift.
IMPLEMENTATIONS = {
    "python-server": Implementation(
        "python-server", "python", "paddle_tpu/inference/server.py",
        commands=frozenset(COMMANDS),
        markers=frozenset(MARKER_BY_NAME),
        statuses=frozenset(STATUSES),
        dtypes=frozenset(DTYPES),
        streaming=True, partial=None),
    "go-client": Implementation(
        "go-client", "go", "clients/go/paddle_tpu/client.go",
        commands=frozenset({CMD_INFER}),
        markers=frozenset({"deadline", "trace", "decode"}),
        statuses=frozenset(STATUSES),
        dtypes=frozenset(DTYPES),
        streaming=True,
        partial="no tenant field (point WithEndpoints at the fleet "
                "router, which stamps tenancy at admission); no KV "
                "snapshot/resume commands (stream resume is "
                "router-internal — clients never see a snapshot frame); "
                "no health command, so the replica phase field is not "
                "yet covered (phase-aware placement is fleet-internal)"),
    "r-client": Implementation(
        "r-client", "r", "clients/r/predictor.R",
        commands=frozenset({CMD_INFER}),
        markers=frozenset({"deadline", "trace", "decode"}),
        statuses=frozenset(STATUSES),
        dtypes=frozenset(DTYPES),
        streaming=True,
        partial="read-only stream path (pd_decode_stream sends i32 "
                "prompts only), no tenant field, no KV snapshot/resume "
                "commands (router-internal), and no health command so "
                "the replica phase field is not yet covered"),
    "c-client": Implementation(
        "c-client", "c++", "paddle_tpu/native/c_api.cc",
        commands=frozenset({CMD_INFER, CMD_HEALTH}),
        markers=frozenset({"deadline", "trace", "decode"}),
        statuses=frozenset(STATUSES),
        dtypes=frozenset(DTYPES),
        streaming=True,
        partial="no tenant field and no reload/stats/metrics/drain/"
                "kv_put/kv_resume commands (operational and "
                "fleet-internal commands belong to the fleet tooling, "
                "not the embedded client); the health body's replica "
                "phase field is not yet covered (parsed as opaque "
                "JSON — phase-aware placement is fleet-internal)"),
}

# ------------------------------------------------------ codec (Python)
# The ONE Python encoder/decoder for the framing above. server.py,
# router.py, bench.py and the test tree all route through these (the
# server re-exports them under its historical underscore names) — the
# bytes they produce are the protocol, bit for bit.


def encode_arrays(arrays):
    """Encode a list of numpy arrays as a cmd-1 array block (u8 count
    then per-array header + row-major data). Exact-widens f16/bf16 to
    f32; raises TypeError on any other unsupported dtype — never a
    silent cast."""
    out = [struct.pack("<B", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        code = CODE_BY_NUMPY.get(a.dtype)
        if code is None:
            if a.dtype.name in WIDEN_TO_F32:
                a = a.astype(np.float32)  # exact widening, not corruption
                code = CODE_BY_NUMPY[a.dtype]
            else:
                raise TypeError(
                    f"dtype {a.dtype} is not encodable on the wire "
                    "(supported: float32, int32, int64, bool, plus "
                    "f16/bf16 widened to f32)")
        out.append(struct.pack("<BB", code, a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(a.tobytes())
    return b"".join(out)


def decode_arrays_off(payload):
    """Decode a cmd-1 array block; returns (arrays, offset past it)."""
    off = 0
    (n,) = struct.unpack_from("<B", payload, off)
    off += 1
    arrays = []
    for _ in range(n):
        code, ndim = struct.unpack_from("<BB", payload, off)
        off += 2
        dims = struct.unpack_from(f"<{ndim}q", payload, off)
        off += 8 * ndim
        dt = NUMPY_BY_CODE[code]
        count = int(np.prod(dims)) if dims else 1
        arr = np.frombuffer(payload, dt, count, off).reshape(dims)
        off += arr.nbytes
        arrays.append(arr)
    return arrays, off


def decode_arrays(payload):
    return decode_arrays_off(payload)[0]


def encode_deadline(timeout_ms):
    """The optional trailing deadline field (marker 0xDD + f64 ms)."""
    return struct.pack("<Bd", DEADLINE_MARKER, float(timeout_ms))


def encode_trace(trace_id):
    """The optional trailing trace-id field (marker 0x1D + u64)."""
    return struct.pack("<BQ", TRACE_MARKER, int(trace_id))


def encode_tenant(tenant_id):
    """The optional trailing tenant-id field (marker 0x7E + u64)."""
    return struct.pack("<BQ", TENANT_MARKER, int(tenant_id))


def encode_decode_opts(max_new_tokens, oneshot=False, snapshot_every=0,
                       handoff=False, speculative=False):
    """The optional trailing decode field (marker 0x5C + u64: low 32
    bits max_new_tokens, bits 32-47 snapshot cadence, bit 61
    speculative opt-in, bit 62 prefill-handoff, bit 63 one-shot)."""
    val = int(max_new_tokens) & 0xFFFFFFFF
    val |= (int(snapshot_every) & DECODE_SNAPSHOT_EVERY_MASK) \
        << DECODE_SNAPSHOT_EVERY_SHIFT
    if speculative:
        val |= DECODE_SPEC_BIT
    if handoff:
        val |= DECODE_HANDOFF_BIT
    if oneshot:
        val |= DECODE_ONESHOT_BIT
    return struct.pack("<BQ", DECODE_MARKER, val)


#: field name -> encoder, for spec-driven permutation tests.
FIELD_ENCODERS = {
    "deadline": encode_deadline,
    "trace": encode_trace,
    "tenant": encode_tenant,
    "decode": lambda v: encode_decode_opts(
        v & 0xFFFFFFFF, bool(v & DECODE_ONESHOT_BIT),
        (v >> DECODE_SNAPSHOT_EVERY_SHIFT) & DECODE_SNAPSHOT_EVERY_MASK,
        bool(v & DECODE_HANDOFF_BIT), bool(v & DECODE_SPEC_BIT)),
}


def decode_request(payload):
    """Decode a cmd-1 infer body: arrays plus the optional trailing
    marker-tagged fields (any order). Returns (arrays,
    budget_seconds_or_None, trace_id_or_None, decode_opts_or_None)
    where decode_opts is ``{"max_new_tokens": n, "oneshot": bool}``.
    Parsing stops at the first unknown marker: old servers ignored
    trailing garbage, and a field this server predates must not be
    misread. The tenant field is parsed and skipped (admission happens
    at the router) so fields AFTER it still parse."""
    arrays, off = decode_arrays_off(payload)
    budget = None
    trace_id = None
    tenant = None
    decode_opts = None
    while len(payload) - off >= FIELD_SIZE:
        marker = payload[off]
        if marker == DEADLINE_MARKER and budget is None:
            (timeout_ms,) = struct.unpack_from("<d", payload, off + 1)
            budget = max(0.0, float(timeout_ms)) / 1000.0
        elif marker == TRACE_MARKER and trace_id is None:
            (tid,) = struct.unpack_from("<Q", payload, off + 1)
            trace_id = tid or None  # 0 = "no trace" on the wire
        elif marker == TENANT_MARKER and tenant is None:
            (tenant,) = struct.unpack_from("<Q", payload, off + 1)
        elif marker == DECODE_MARKER and decode_opts is None:
            (val,) = struct.unpack_from("<Q", payload, off + 1)
            decode_opts = {
                "max_new_tokens": int(val & 0xFFFFFFFF) or None,
                "oneshot": bool(val & DECODE_ONESHOT_BIT),
                "handoff": bool(val & DECODE_HANDOFF_BIT),
                "speculative": bool(val & DECODE_SPEC_BIT),
                "snapshot_every": int(
                    (val >> DECODE_SNAPSHOT_EVERY_SHIFT)
                    & DECODE_SNAPSHOT_EVERY_MASK),
            }
        else:
            break
        off += FIELD_SIZE
    return arrays, budget, trace_id, decode_opts


def is_kv_snapshot(payload):
    """Does this payload start with a kv-snapshot block? (The router's
    frame-classification test: a token chunk's first byte is its u8
    array count, never the magic.)"""
    return len(payload) > 0 and payload[0] == KV_FRAME_MAGIC


def encode_kv_snapshot(header, arrays):
    """Encode one kv-snapshot block: magic + version + length-prefixed
    JSON header + the standard array block (``[prompt, generated tail,
    KV pages...]``). ``header`` must carry every KV_HEADER_REQUIRED
    key; the version key is stamped here."""
    hdr = dict(header)
    hdr["v"] = KV_SNAPSHOT_VERSION
    missing = [k for k in KV_HEADER_REQUIRED if k not in hdr]
    if missing:
        raise ValueError(f"kv-snapshot header missing keys: {missing}")
    blob = json.dumps(hdr, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return (struct.pack("<BHI", KV_FRAME_MAGIC, KV_SNAPSHOT_VERSION,
                        len(blob))
            + blob + encode_arrays(arrays))


def decode_kv_snapshot_off(payload, off=0):
    """Decode one kv-snapshot block at ``off``; returns (header dict,
    arrays, offset past the block). Raises ValueError on a bad magic,
    an unknown version, or a short/garbled header — a permanent
    request error, not a refusal."""
    if len(payload) - off < 7:
        raise ValueError("kv-snapshot block truncated")
    magic, version, hdr_len = struct.unpack_from("<BHI", payload, off)
    if magic != KV_FRAME_MAGIC:
        raise ValueError(f"kv-snapshot magic mismatch: {magic}")
    if version != KV_SNAPSHOT_VERSION:
        raise ValueError(f"kv-snapshot version {version} is not "
                         f"{KV_SNAPSHOT_VERSION}")
    off += 7
    if len(payload) - off < hdr_len:
        raise ValueError("kv-snapshot header truncated")
    try:
        header = json.loads(bytes(payload[off:off + hdr_len]))
    except ValueError as e:
        raise ValueError(f"kv-snapshot header is not JSON: {e}")
    if not isinstance(header, dict):
        raise ValueError("kv-snapshot header is not a JSON object")
    missing = [k for k in KV_HEADER_REQUIRED if k not in header]
    if missing:
        raise ValueError(f"kv-snapshot header missing keys: {missing}")
    off += hdr_len
    arrays, n = decode_arrays_off(payload[off:])
    return header, arrays, off + n


def decode_kv_snapshot_header(payload):
    """Header-only parse of a kv-snapshot block (the array block is
    not touched): what the router's dedup arithmetic needs per held
    snapshot without paying an array copy. Same ValueError behaviour
    as :func:`decode_kv_snapshot_off`."""
    if len(payload) < 7:
        raise ValueError("kv-snapshot block truncated")
    magic, version, hdr_len = struct.unpack_from("<BHI", payload, 0)
    if magic != KV_FRAME_MAGIC:
        raise ValueError(f"kv-snapshot magic mismatch: {magic}")
    if version != KV_SNAPSHOT_VERSION:
        raise ValueError(f"kv-snapshot version {version} is not "
                         f"{KV_SNAPSHOT_VERSION}")
    if len(payload) - 7 < hdr_len:
        raise ValueError("kv-snapshot header truncated")
    try:
        header = json.loads(bytes(payload[7:7 + hdr_len]))
    except ValueError as e:
        raise ValueError(f"kv-snapshot header is not JSON: {e}")
    if not isinstance(header, dict):
        raise ValueError("kv-snapshot header is not a JSON object")
    missing = [k for k in KV_HEADER_REQUIRED if k not in header]
    if missing:
        raise ValueError(f"kv-snapshot header missing keys: {missing}")
    return header


def decode_kv_resume(payload):
    """Decode a cmd kv_resume body: one kv-snapshot block then the
    optional trailing marker fields (same loop and stop-at-unknown
    rule as an infer body). Returns (header, arrays,
    budget_seconds_or_None, trace_id_or_None, decode_opts_or_None,
    snapshot_end_offset) — the last element lets a server slice the
    raw block (``payload[:end]``) to re-validate/restore without
    re-encoding it."""
    header, arrays, snap_end = decode_kv_snapshot_off(payload)
    off = snap_end
    budget = None
    trace_id = None
    decode_opts = None
    while len(payload) - off >= FIELD_SIZE:
        marker = payload[off]
        if marker == DEADLINE_MARKER and budget is None:
            (timeout_ms,) = struct.unpack_from("<d", payload, off + 1)
            budget = max(0.0, float(timeout_ms)) / 1000.0
        elif marker == TRACE_MARKER and trace_id is None:
            (tid,) = struct.unpack_from("<Q", payload, off + 1)
            trace_id = tid or None
        elif marker == DECODE_MARKER and decode_opts is None:
            (val,) = struct.unpack_from("<Q", payload, off + 1)
            decode_opts = {
                "max_new_tokens": int(val & 0xFFFFFFFF) or None,
                "oneshot": bool(val & DECODE_ONESHOT_BIT),
                "handoff": bool(val & DECODE_HANDOFF_BIT),
                "speculative": bool(val & DECODE_SPEC_BIT),
                "snapshot_every": int(
                    (val >> DECODE_SNAPSHOT_EVERY_SHIFT)
                    & DECODE_SNAPSHOT_EVERY_MASK),
            }
        else:
            break
        off += FIELD_SIZE
    return header, arrays, budget, trace_id, decode_opts, snap_end


def build_request(cmd, payload=b""):
    """One complete request frame: u32 body_len | u8 cmd | payload."""
    if cmd not in COMMANDS:
        raise ValueError(f"unknown wire command {cmd}")
    return struct.pack("<IB", 1 + len(payload), cmd) + payload


def build_reply(status, payload=b""):
    """One complete reply frame: u32 body_len | u8 status | payload."""
    if status not in STATUSES:
        raise ValueError(f"unknown wire status {status}")
    return struct.pack("<IB", 1 + len(payload), status) + payload


# ----------------------------------------------------- doc generation

def markdown_table():
    """The README "Wire protocol" tables, generated from the tables
    above (tests/test_wire_spec.py asserts the README copy matches —
    the KNOWN_FAILURES discipline applied to docs)."""
    lines = [
        "Framing (little-endian): request `u32 body_len | u8 cmd | "
        "payload`; response `u32 body_len | u8 status | payload`. "
        "Commands, statuses, trailing fields, and dtype codes below "
        "are generated from `paddle_tpu/inference/wire_spec.py` "
        f"(spec v{SPEC_VERSION}) — the machine-checked source of "
        "truth the `--protocol` lint diffs every implementation "
        "against.",
        "",
        "| cmd | name | request payload | response |",
        "|-----|------|-----------------|----------|",
    ]
    for c in sorted(COMMANDS):
        w = COMMANDS[c]
        lines.append(f"| {w.code} | `{w.name}` | {w.request} "
                     f"| {w.response} |")
    lines += [
        "",
        "| status | name | meaning |",
        "|--------|------|---------|",
    ]
    for s in sorted(STATUSES):
        w = STATUSES[s]
        term = "terminal" if w.terminal else "non-terminal"
        lines.append(f"| {w.code} | `{w.name}` ({term}) | {w.doc} |")
    lines += [
        "",
        "| marker | field | payload | meaning |",
        "|--------|-------|---------|---------|",
    ]
    for b in sorted(MARKERS):
        m = MARKERS[b]
        payload = {"<d": "f64", "<Q": "u64"}[m.fmt]
        lines.append(f"| `0x{m.byte:02X}` | `{m.name}` | {payload} "
                     f"| {m.doc} |")
    lines += [
        "",
        "| dtype code | name | bytes/elem |",
        "|------------|------|------------|",
    ]
    for c in sorted(DTYPES):
        d = DTYPES[c]
        lines.append(f"| {d.code} | `{d.name}` | {d.size} |")
    lines += [
        "",
        "Implementations (drift-gated by `ci_gate --protocol`; "
        "`partial` gaps are declared in the spec, not silent):",
        "",
        "| implementation | path | commands | declared gaps |",
        "|----------------|------|----------|---------------|",
    ]
    for name in sorted(IMPLEMENTATIONS):
        i = IMPLEMENTATIONS[name]
        cmds = ", ".join(str(c) for c in sorted(i.commands))
        lines.append(f"| {i.name} | `{i.path}` | {cmds} "
                     f"| {i.partial or '—'} |")
    return "\n".join(lines)
