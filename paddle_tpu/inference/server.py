"""Inference server: serves a saved model over the same length-prefixed
TCP framing as the PS service, so the C API (native/c_api.cc), Go/R
clients, or any socket speaker can run predictions against the TPU
process.

Reference: paddle/fluid/inference/capi/ + go/paddle/predictor.go talk to
an in-process C++ predictor; on TPU the predictor owns device state and
compiled programs, so out-of-process callers go through this service
instead (the architecture real TPU serving uses).

wire format (little-endian):
  request:  u32 body_len | u8 cmd | payload
  cmds: 1 infer  payload = u8 n_inputs, per input:
            u8 dtype (0=f32, 1=i32) | u8 ndim | i64 dims[ndim] | data
        7 stop
  response: u32 body_len | u8 status | (cmd 1: same per-output encoding)
"""
import os
import socket
import struct
import threading

import numpy as np

_DTYPES = {0: np.float32, 1: np.int32}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1}

# Hardening knobs: a 4-byte length prefix from a buggy/malicious client
# must not trigger an unbounded allocation, and a stalled client must
# not pin a handler thread forever.
MAX_BODY_BYTES = int(os.environ.get("PADDLE_TPU_SERVER_MAX_BODY",
                                    64 * 1024 * 1024))
RECV_TIMEOUT = float(os.environ.get("PADDLE_TPU_SERVER_RECV_TIMEOUT", 30.0))
DRAIN_TIMEOUT = float(os.environ.get("PADDLE_TPU_SERVER_DRAIN_TIMEOUT", 10.0))


class BodyTooLarge(ValueError):
    pass


def _read_all(sock, n, limit=None):
    if limit is not None and n > limit:
        raise BodyTooLarge(f"frame of {n} bytes exceeds cap {limit}")
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _encode_arrays(arrays):
    out = [struct.pack("<B", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        code = _DTYPE_CODES.get(a.dtype)
        if code is None:
            a = a.astype(np.float32)
            code = 0
        out.append(struct.pack("<BB", code, a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(a.tobytes())
    return b"".join(out)


def _decode_arrays(payload):
    off = 0
    (n,) = struct.unpack_from("<B", payload, off)
    off += 1
    arrays = []
    for _ in range(n):
        code, ndim = struct.unpack_from("<BB", payload, off)
        off += 2
        dims = struct.unpack_from(f"<{ndim}q", payload, off)
        off += 8 * ndim
        dt = _DTYPES[code]
        count = int(np.prod(dims)) if dims else 1
        arr = np.frombuffer(payload, dt, count, off).reshape(dims)
        off += arr.nbytes
        arrays.append(arr)
    return arrays


class PredictorServer:
    """Serve `predictor` (an inference.Predictor or any callable taking
    numpy arrays and returning a list of numpy arrays) on a TCP port."""

    def __init__(self, run_fn, port=0, host="127.0.0.1",
                 max_body=MAX_BODY_BYTES, recv_timeout=RECV_TIMEOUT):
        self._run = run_fn
        self._max_body = max_body
        self._recv_timeout = recv_timeout
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns = {}  # thread -> {"conn": socket, "busy": bool}
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            with self._conns_lock:
                self._conns[t] = {"conn": conn, "busy": False}
            t.start()

    def _set_busy(self, busy):
        with self._conns_lock:
            ent = self._conns.get(threading.current_thread())
            if ent is not None:
                ent["busy"] = busy

    def _handle(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                # idle between frames: block without timeout — keep-alive
                # connections may sit quiet for minutes (stop() unblocks
                # this recv by closing the socket). Once the first header
                # byte arrives, a frame is in flight: a peer that stalls
                # mid-frame times out instead of pinning this thread.
                conn.settimeout(None)
                first = conn.recv(1)
                if not first:
                    raise ConnectionError("peer closed")
                conn.settimeout(self._recv_timeout)
                (blen,) = struct.unpack("<I", first + _read_all(conn, 3))
                if blen == 0:
                    # malformed (a body always has at least the cmd
                    # byte) but the stream is still in sync: report and
                    # keep serving
                    conn.sendall(struct.pack("<IB", 1, 1))
                    continue
                self._set_busy(True)  # a frame is in flight: drain waits
                try:
                    body = _read_all(conn, blen, limit=self._max_body)
                except BodyTooLarge:
                    # cap exceeded: error status, then close — the rest
                    # of the oversized frame is unread, so the stream
                    # cannot be resynced
                    conn.sendall(struct.pack("<IB", 1, 1))
                    return
                cmd = body[0]
                if cmd == 7:
                    conn.sendall(struct.pack("<IB", 1, 0))
                    threading.Thread(target=self.stop, daemon=True).start()
                    return
                if cmd != 1:
                    conn.sendall(struct.pack("<IB", 1, 1))
                    self._set_busy(False)
                    continue
                try:
                    inputs = _decode_arrays(body[1:])
                    outputs = self._run(*inputs)
                    if not isinstance(outputs, (list, tuple)):
                        outputs = [outputs]
                    outputs = [np.asarray(o._value if hasattr(o, "_value")
                                          else o) for o in outputs]
                    enc = _encode_arrays(outputs)
                    conn.sendall(struct.pack("<IB", 1 + len(enc), 0) + enc)
                except Exception:  # noqa: BLE001 - protocol error status
                    conn.sendall(struct.pack("<IB", 1, 1))
                self._set_busy(False)
        except socket.timeout:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.pop(threading.current_thread(), None)

    def stop(self, drain=True, timeout=DRAIN_TIMEOUT):
        """Graceful shutdown: stop accepting, let requests that are
        mid-processing finish (up to `timeout`), force-close idle
        keep-alive connections — a rolling restart neither drops a
        response mid-write nor hangs on a silent client."""
        import time as time_mod

        self._stop.set()
        try:
            self._sock.close()  # unblocks accept(); no new connections
        except OSError:
            pass
        if not drain:
            return
        me = threading.current_thread()
        deadline = time_mod.monotonic() + timeout
        with self._conns_lock:
            entries = [(t, e) for t, e in self._conns.items() if t is not me]
        for t, ent in entries:
            if ent["busy"]:
                t.join(max(0.0, deadline - time_mod.monotonic()))
        # whoever is left is idle (blocked waiting for the next frame) or
        # overran the drain window — unblock by closing the socket
        with self._conns_lock:
            leftover = [e["conn"] for t, e in self._conns.items()
                        if t is not me]
        for c in leftover:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


def serve_model(path_prefix, port=0):
    """Load a jit-saved model and serve it (the C API's server side)."""
    from ..jit import load as jit_load

    layer = jit_load(path_prefix)

    def run(*arrays):
        out = layer(*arrays)
        return out if isinstance(out, (list, tuple)) else [out]

    return PredictorServer(run, port=port)
