"""Inference server: serves a saved model over the same length-prefixed
TCP framing as the PS service, so the C API (native/c_api.cc), Go/R
clients, or any socket speaker can run predictions against the TPU
process.

Reference: paddle/fluid/inference/capi/ + go/paddle/predictor.go talk to
an in-process C++ predictor; on TPU the predictor owns device state and
compiled programs, so out-of-process callers go through this service
instead (the architecture real TPU serving uses).

wire format (little-endian):
  request:  u32 body_len | u8 cmd | payload
  cmds: 1 infer  payload = u8 n_inputs, per input:
            u8 dtype (0=f32, 1=i32, 2=i64, 3=bool) | u8 ndim |
            i64 dims[ndim] | data
        5 stats  payload = (empty); response body is a UTF-8 JSON
            object with the batching-engine counters (per-bucket
            compiles/hits/latency, queue depth, shed_count) — or
            {"engine": null} when serving without an engine
        7 stop
  response: u32 body_len | u8 status | (cmd 1: same per-output encoding)
  status: 0 ok | 1 error | 2 overloaded (request shed by the batching
          engine's bounded queue — back off and retry)
"""
import json
import os
import socket
import struct
import threading

import numpy as np

from .batching import EngineOverloaded

_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64, 3: np.bool_}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                np.dtype(np.int64): 2, np.dtype(np.bool_): 3}
# exact widenings only: half floats encode as f32 without corruption;
# anything else (f64, unsigned, complex...) must raise, never silently
# cast (the old behavior corrupted i64 token ids through an f32 cast)
_WIDEN_TO_F32 = {"float16", "bfloat16"}

STATUS_OK = 0
STATUS_ERROR = 1
STATUS_OVERLOADED = EngineOverloaded.status_code  # 2

# Hardening knobs: a 4-byte length prefix from a buggy/malicious client
# must not trigger an unbounded allocation, and a stalled client must
# not pin a handler thread forever.
MAX_BODY_BYTES = int(os.environ.get("PADDLE_TPU_SERVER_MAX_BODY",
                                    64 * 1024 * 1024))
RECV_TIMEOUT = float(os.environ.get("PADDLE_TPU_SERVER_RECV_TIMEOUT", 30.0))
DRAIN_TIMEOUT = float(os.environ.get("PADDLE_TPU_SERVER_DRAIN_TIMEOUT", 10.0))


class BodyTooLarge(ValueError):
    pass


def _read_all(sock, n, limit=None):
    if limit is not None and n > limit:
        raise BodyTooLarge(f"frame of {n} bytes exceeds cap {limit}")
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _encode_arrays(arrays):
    out = [struct.pack("<B", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        code = _DTYPE_CODES.get(a.dtype)
        if code is None:
            if a.dtype.name in _WIDEN_TO_F32:
                a = a.astype(np.float32)  # exact widening, not corruption
                code = 0
            else:
                raise TypeError(
                    f"dtype {a.dtype} is not encodable on the wire "
                    "(supported: float32, int32, int64, bool, plus "
                    "f16/bf16 widened to f32)")
        out.append(struct.pack("<BB", code, a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(a.tobytes())
    return b"".join(out)


def _decode_arrays(payload):
    off = 0
    (n,) = struct.unpack_from("<B", payload, off)
    off += 1
    arrays = []
    for _ in range(n):
        code, ndim = struct.unpack_from("<BB", payload, off)
        off += 2
        dims = struct.unpack_from(f"<{ndim}q", payload, off)
        off += 8 * ndim
        dt = _DTYPES[code]
        count = int(np.prod(dims)) if dims else 1
        arr = np.frombuffer(payload, dt, count, off).reshape(dims)
        off += arr.nbytes
        arrays.append(arr)
    return arrays


class PredictorServer:
    """Serve `predictor` (an inference.Predictor or any callable taking
    numpy arrays and returning a list of numpy arrays) on a TCP port.

    With ``engine`` (an inference.batching.BatchingEngine), cmd-1 infer
    requests from ALL connections route through the engine's scheduler:
    concurrent clients coalesce into padded shape-bucket batches, the
    bounded queue sheds overload as wire status 2 instead of queuing
    unboundedly, and the ``stats`` command (cmd 5) exposes the
    per-bucket compile/hit/latency counters."""

    def __init__(self, run_fn, port=0, host="127.0.0.1",
                 max_body=MAX_BODY_BYTES, recv_timeout=RECV_TIMEOUT,
                 engine=None, own_engine=False):
        self._run = run_fn
        self._engine = engine
        # own_engine: this server is the engine's only handle (serve_model
        # builds one per server) and must close it on stop, or its
        # scheduler thread + compiled programs leak per server lifecycle
        self._own_engine = own_engine and engine is not None
        self._max_body = max_body
        self._recv_timeout = recv_timeout
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns = {}  # thread -> {"conn": socket, "busy": bool}
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            with self._conns_lock:
                self._conns[t] = {"conn": conn, "busy": False}
            t.start()

    def _set_busy(self, busy):
        with self._conns_lock:
            ent = self._conns.get(threading.current_thread())
            if ent is not None:
                ent["busy"] = busy

    def _stats_json(self):
        """Body of the `stats` wire command (cmd 5)."""
        if self._engine is None:
            return json.dumps({"engine": None})
        return self._engine.stats_json()

    def _handle(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                # idle between frames: block without timeout — keep-alive
                # connections may sit quiet for minutes (stop() unblocks
                # this recv by closing the socket). Once the first header
                # byte arrives, a frame is in flight: a peer that stalls
                # mid-frame times out instead of pinning this thread.
                conn.settimeout(None)
                first = conn.recv(1)
                if not first:
                    raise ConnectionError("peer closed")
                conn.settimeout(self._recv_timeout)
                (blen,) = struct.unpack("<I", first + _read_all(conn, 3))
                if blen == 0:
                    # malformed (a body always has at least the cmd
                    # byte) but the stream is still in sync: report and
                    # keep serving
                    conn.sendall(struct.pack("<IB", 1, 1))
                    continue
                self._set_busy(True)  # a frame is in flight: drain waits
                try:
                    body = _read_all(conn, blen, limit=self._max_body)
                except BodyTooLarge:
                    # cap exceeded: error status, then close — the rest
                    # of the oversized frame is unread, so the stream
                    # cannot be resynced
                    conn.sendall(struct.pack("<IB", 1, 1))
                    return
                cmd = body[0]
                if cmd == 7:
                    conn.sendall(struct.pack("<IB", 1, 0))
                    threading.Thread(target=self.stop, daemon=True).start()
                    return
                if cmd == 5:
                    enc = self._stats_json().encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc), 0) + enc)
                    self._set_busy(False)
                    continue
                if cmd != 1:
                    conn.sendall(struct.pack("<IB", 1, 1))
                    self._set_busy(False)
                    continue
                try:
                    inputs = _decode_arrays(body[1:])
                    if self._engine is not None:
                        outputs = self._engine.infer(inputs)
                    else:
                        outputs = self._run(*inputs)
                    if not isinstance(outputs, (list, tuple)):
                        outputs = [outputs]
                    outputs = [np.asarray(o._value if hasattr(o, "_value")
                                          else o) for o in outputs]
                    enc = _encode_arrays(outputs)
                    conn.sendall(struct.pack("<IB", 1 + len(enc), 0) + enc)
                except EngineOverloaded:
                    # load shed: a fast, explicit rejection the client
                    # can retry — never an unbounded queue
                    conn.sendall(struct.pack("<IB", 1, STATUS_OVERLOADED))
                except Exception:  # noqa: BLE001 - protocol error status
                    conn.sendall(struct.pack("<IB", 1, 1))
                self._set_busy(False)
        except socket.timeout:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.pop(threading.current_thread(), None)

    def stop(self, drain=True, timeout=DRAIN_TIMEOUT):
        """Graceful shutdown: stop accepting, let requests that are
        mid-processing finish (up to `timeout`), force-close idle
        keep-alive connections — a rolling restart neither drops a
        response mid-write nor hangs on a silent client."""
        import time as time_mod

        self._stop.set()
        try:
            self._sock.close()  # unblocks accept(); no new connections
        except OSError:
            pass
        if not drain:
            if self._own_engine:
                self._engine.close()
            return
        me = threading.current_thread()
        deadline = time_mod.monotonic() + timeout
        with self._conns_lock:
            entries = [(t, e) for t, e in self._conns.items() if t is not me]
        for t, ent in entries:
            if ent["busy"]:
                t.join(max(0.0, deadline - time_mod.monotonic()))
        # whoever is left is idle (blocked waiting for the next frame) or
        # overran the drain window — unblock by closing the socket
        with self._conns_lock:
            leftover = [e["conn"] for t, e in self._conns.items()
                        if t is not me]
        for c in leftover:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._own_engine:
            # handlers are drained/unblocked; pending engine requests
            # still fire (close() lets partial batches complete)
            self._engine.close()


def serve_model(path_prefix, port=0, dynamic_batching=False,
                max_batch_size=32, max_wait_ms=2.0, max_queue=256,
                warmup=True):
    """Load a jit-saved model and serve it (the C API's server side).

    With ``dynamic_batching=True`` (needs a batch-polymorphic save, see
    jit.save) all connections share one BatchingEngine: requests
    coalesce into padded shape-bucket batches, declared buckets are
    precompiled up front, and saturation sheds as wire status 2."""
    from ..jit import load as jit_load

    layer = jit_load(path_prefix)

    def run(*arrays):
        out = layer(*arrays)
        return out if isinstance(out, (list, tuple)) else [out]

    engine = None
    if dynamic_batching:
        from .batching import BatchingEngine

        engine = BatchingEngine.for_layer(layer,
                                          max_batch_size=max_batch_size,
                                          max_wait_ms=max_wait_ms,
                                          max_queue=max_queue)
        if warmup:
            engine.warmup()
    return PredictorServer(run, port=port, engine=engine,
                           own_engine=engine is not None)
