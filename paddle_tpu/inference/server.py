"""Inference server: serves a saved model over the same length-prefixed
TCP framing as the PS service, so the C API (native/c_api.cc), Go/R
clients, or any socket speaker can run predictions against the TPU
process.

Reference: paddle/fluid/inference/capi/ + go/paddle/predictor.go talk to
an in-process C++ predictor; on TPU the predictor owns device state and
compiled programs, so out-of-process callers go through this service
instead (the architecture real TPU serving uses).

wire format (little-endian):
  request:  u32 body_len | u8 cmd | payload
  cmds: 1 infer  payload = u8 n_inputs, per input:
            u8 dtype (0=f32, 1=i32) | u8 ndim | i64 dims[ndim] | data
        7 stop
  response: u32 body_len | u8 status | (cmd 1: same per-output encoding)
"""
import socket
import struct
import threading

import numpy as np

_DTYPES = {0: np.float32, 1: np.int32}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1}


def _read_all(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _encode_arrays(arrays):
    out = [struct.pack("<B", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        code = _DTYPE_CODES.get(a.dtype)
        if code is None:
            a = a.astype(np.float32)
            code = 0
        out.append(struct.pack("<BB", code, a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(a.tobytes())
    return b"".join(out)


def _decode_arrays(payload):
    off = 0
    (n,) = struct.unpack_from("<B", payload, off)
    off += 1
    arrays = []
    for _ in range(n):
        code, ndim = struct.unpack_from("<BB", payload, off)
        off += 2
        dims = struct.unpack_from(f"<{ndim}q", payload, off)
        off += 8 * ndim
        dt = _DTYPES[code]
        count = int(np.prod(dims)) if dims else 1
        arr = np.frombuffer(payload, dt, count, off).reshape(dims)
        off += arr.nbytes
        arrays.append(arr)
    return arrays


class PredictorServer:
    """Serve `predictor` (an inference.Predictor or any callable taking
    numpy arrays and returning a list of numpy arrays) on a TCP port."""

    def __init__(self, run_fn, port=0, host="127.0.0.1"):
        self._run = run_fn
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                (blen,) = struct.unpack("<I", _read_all(conn, 4))
                body = _read_all(conn, blen)
                cmd = body[0]
                if cmd == 7:
                    conn.sendall(struct.pack("<IB", 1, 0))
                    self.stop()
                    return
                if cmd != 1:
                    conn.sendall(struct.pack("<IB", 1, 1))
                    continue
                try:
                    inputs = _decode_arrays(body[1:])
                    outputs = self._run(*inputs)
                    if not isinstance(outputs, (list, tuple)):
                        outputs = [outputs]
                    outputs = [np.asarray(o._value if hasattr(o, "_value")
                                          else o) for o in outputs]
                    enc = _encode_arrays(outputs)
                    conn.sendall(struct.pack("<IB", 1 + len(enc), 0) + enc)
                except Exception:  # noqa: BLE001 - protocol error status
                    conn.sendall(struct.pack("<IB", 1, 1))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def serve_model(path_prefix, port=0):
    """Load a jit-saved model and serve it (the C API's server side)."""
    from ..jit import load as jit_load

    layer = jit_load(path_prefix)

    def run(*arrays):
        out = layer(*arrays)
        return out if isinstance(out, (list, tuple)) else [out]

    return PredictorServer(run, port=port)
