"""Inference server: serves a saved model over the same length-prefixed
TCP framing as the PS service, so the C API (native/c_api.cc), Go/R
clients, or any socket speaker can run predictions against the TPU
process.

Reference: paddle/fluid/inference/capi/ + go/paddle/predictor.go talk to
an in-process C++ predictor; on TPU the predictor owns device state and
compiled programs, so out-of-process callers go through this service
instead (the architecture real TPU serving uses).

wire format (little-endian):
  request:  u32 body_len | u8 cmd | payload
  cmds: 1 infer  payload = u8 n_inputs, per input:
            u8 dtype (0=f32, 1=i32, 2=i64, 3=bool) | u8 ndim |
            i64 dims[ndim] | data
          ... optionally followed by trailing fields, each tagged by a
          marker byte and parseable in any order:
            u8 0xDD | f64 timeout_ms (relative budget; the server
            computes the absolute deadline at receipt and drops the
            request without dispatch once it expires)
            u8 0x1D | u64 trace_id (non-zero; tags the request's
            obs.tracing spans across enqueue/batch/execute/reply so
            one request can be followed through the engine)
            u8 0x7E | u64 tenant_id (inference.fleet.tenant_id(name);
            the fleet router keys admission control and per-tenant
            goodput accounting on it; a direct replica parses and
            ignores it)
            u8 0x5C | u64 decode opts (continuous-batching decode
            request, servers with a decode engine only: low 32 bits =
            max_new_tokens, bit 63 set = ONE-SHOT — collect the whole
            sequence into today's single reply. Without bit 63 the
            reply is a CHUNKED STREAM: zero or more frames with
            status 3 (one token-array chunk each, a frame per token
            batch), terminated by exactly one frame with status 0
            (the final chunk, possibly a zero-length array) or 1/2 on
            error/shed — the client concatenates the chunks. Input
            array 0 is the prompt (1-D int32/int64 token ids; the
            token chunks echo its dtype), further arrays are the
            model's per-sequence features. The 0xDD deadline field
            becomes a PER-TOKEN budget: time to first token and every
            inter-token gap.)
          Old servers ignore the trailing bytes; old clients simply
          omit them — both directions stay compatible: only a client
          that sent 0x5C without bit 63 ever sees status 3.
        3 health  payload = (empty); response body is UTF-8 JSON
            liveness/readiness: scheduler alive + heartbeat age,
            quarantined buckets, queue depth, draining flag, plus
            ``accepting`` (false once a drain began — route no new
            work here, but in-flight requests still finish) and
            ``draining_deadline_s`` (seconds the drain will still
            wait; null when not draining). Absent fields mean
            accepting: servers predating them never drain-announce.
        4 reload  payload = optional UTF-8 model prefix (empty = same
            prefix); the server loads + warms the new model OFF TO THE
            SIDE, swaps it in atomically, then drains the old engine —
            zero dropped requests, zero post-swap cold compiles for
            declared buckets. Response body is UTF-8 JSON.
        5 stats  payload = (empty); response body is a UTF-8 JSON
            object with the batching-engine counters (per-bucket
            compiles/hits/latency, breaker states, queue depth,
            shed_count) — or {"engine": null} when serving without an
            engine
        8 drain  payload = optional f64 drain budget in seconds; marks
            the server not-accepting (health: accepting=false,
            draining_deadline_s counts down) WITHOUT stopping it —
            in-flight and even newly-arriving requests still serve,
            but a fleet router that honors the flag stops routing here
            (how the fleet scales down / hot-reloads with zero drops:
            drain, wait for the router's in-flight count to reach
            zero, then reload or cmd-7 stop). Response is the health
            JSON. `undrain` = cmd 8 with f64 < 0: re-open admission.
        9 kv_put  payload = one kv-snapshot block (wire_spec
            "KV snapshots"); stateless preflight: the server validates
            the block against its own identity (model fingerprint,
            weights digest, quant mode, mesh) and limits without
            decoding anything. status 0 + the JSON header echoed =
            this replica could resume it; 2 = valid block, wrong
            replica (identity/capacity skew — try another); 1 =
            malformed block.
        10 kv_resume  payload = one kv-snapshot block, then the same
            optional trailing marker fields as cmd 1. The server
            restores the sequence at its exact position and replies
            EXACTLY like a streaming cmd-1 decode request (status-3
            chunks carrying only tokens AFTER the snapshot position,
            then one terminal frame); an identity skew is a status-2
            terminal, never silent wrong tokens. Servers without a
            decode engine answer status 1.
        6 metrics  payload = (empty); response body is the Prometheus
            text exposition (format 0.0.4) of the process obs registry:
            engine counters, server conn/frame counters, resilience
            counters, goodput, compile-ledger totals. The same text is
            served over HTTP by ``serve_model(metrics_port=...)``.
        7 stop
  response: u32 body_len | u8 status | (cmd 1: same per-output encoding)
  status: 0 ok | 1 error | 2 retryable (request shed by the batching
          engine's bounded queue, a quarantined bucket, a scheduler
          restart, or an expired deadline — back off and retry)
          | 3 stream chunk, more frames follow (streaming decode
          replies only — never sent unless the request carried the
          0x5C field without its one-shot bit)
"""
import json
import os
import socket
import struct
import threading
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import prometheus as obs_prometheus
from ..obs import tracing as obs_tracing
from .batching import EngineClosed, RetryableError
# every wire constant comes from the ONE machine-readable spec
# (wire_spec.py) — the protocol lint (tools/tracelint.py --protocol)
# fails on any hardcoded wire literal reintroduced here, so this file
# can never drift from the spec (or from the Go/R/C clients, which the
# same lint diffs against it)
from . import wire_spec
from .wire_spec import (CMD_DRAIN, CMD_HEALTH, CMD_INFER, CMD_KV_PUT,
                        CMD_KV_RESUME, CMD_METRICS, CMD_RELOAD, CMD_STATS,
                        CMD_STOP, DEADLINE_MARKER, DECODE_MARKER,
                        DECODE_ONESHOT_BIT, TENANT_MARKER, TRACE_MARKER)

# historical aliases (tests, bench.py, and the router import these
# names from here): the tables live in wire_spec now
_DTYPES = wire_spec.NUMPY_BY_CODE
_DTYPE_CODES = wire_spec.CODE_BY_NUMPY
_WIDEN_TO_F32 = wire_spec.WIDEN_TO_F32

STATUS_OK = wire_spec.STATUS_OK
STATUS_ERROR = wire_spec.STATUS_ERROR
STATUS_OVERLOADED = wire_spec.STATUS_RETRYABLE  # == RetryableError.status_code
STATUS_STREAM = wire_spec.STATUS_STREAM  # non-final streaming chunk

# Machine-checked lock order (tools/tracelint.py --concurrency, TPU309):
# one reload at a time (coarse, dedicated) > the backend swap lock (held
# only for the pointer swap) > the engine's own lock. The serving path
# (_handle/_infer) takes _backend_lock alone, so reload's long
# load+warmup never stalls a request.
# tpu-lock-order: PredictorServer._reload_lock < PredictorServer._backend_lock  # swap happens inside a reload
# tpu-lock-order: PredictorServer._reload_lock < BatchingEngine._lock  # reload warms/closes engines
# tpu-lock-order: PredictorServer._backend_lock < Metric._lock  # counters bump under the swap lock

# Hardening knobs: a 4-byte length prefix from a buggy/malicious client
# must not trigger an unbounded allocation, and a stalled client must
# not pin a handler thread forever.
MAX_BODY_BYTES = int(os.environ.get("PADDLE_TPU_SERVER_MAX_BODY",
                                    64 * 1024 * 1024))
RECV_TIMEOUT = float(os.environ.get("PADDLE_TPU_SERVER_RECV_TIMEOUT", 30.0))
DRAIN_TIMEOUT = float(os.environ.get("PADDLE_TPU_SERVER_DRAIN_TIMEOUT", 10.0))


class BodyTooLarge(ValueError):
    pass


def _read_all(sock, n, limit=None):
    if limit is not None and n > limit:
        raise BodyTooLarge(f"frame of {n} bytes exceeds cap {limit}")
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# The codec lives in wire_spec (the one Python encoder/decoder of the
# framing); these historical underscore names are what the rest of the
# repo — router, bench.py, the serving test tree — imports from here.
_encode_arrays = wire_spec.encode_arrays
_encode_deadline = wire_spec.encode_deadline
_encode_trace = wire_spec.encode_trace
_encode_tenant = wire_spec.encode_tenant
_encode_decode_opts = wire_spec.encode_decode_opts
_decode_arrays_off = wire_spec.decode_arrays_off
_decode_arrays = wire_spec.decode_arrays
_decode_request = wire_spec.decode_request


class PredictorServer:
    """Serve `predictor` (an inference.Predictor or any callable taking
    numpy arrays and returning a list of numpy arrays) on a TCP port.

    With ``engine`` (an inference.batching.BatchingEngine), cmd-1 infer
    requests from ALL connections route through the engine's scheduler:
    concurrent clients coalesce into padded shape-bucket batches, the
    bounded queue sheds overload as wire status 2 instead of queuing
    unboundedly, and the ``stats`` command (cmd 5) exposes the
    per-bucket compile/hit/latency counters.

    With ``loader`` (a callable ``prefix -> (run_fn, engine_or_None)``,
    supplied by :func:`serve_model`), the ``reload`` wire command (cmd
    4) hot-swaps the served model: the new model loads and warms up off
    to the side, the (run_fn, engine) pair swaps atomically, and the old
    engine drains — in-flight requests complete on the old programs, a
    handler that raced the swap retries once on the new engine, and
    declared buckets are precompiled so no post-swap request pays a
    cold compile."""

    # tpu-resource: acquires=router_socket
    def __init__(self, run_fn, port=0, host="127.0.0.1",
                 max_body=MAX_BODY_BYTES, recv_timeout=RECV_TIMEOUT,
                 engine=None, own_engine=False, loader=None, prefix=None,
                 decode_engine=None, own_decode_engine=False, phase=None):
        self._run = run_fn
        self._engine = engine
        # phase: this replica's pool in a disaggregated fleet
        # (wire_spec.REPLICA_PHASES; env default
        # PADDLE_TPU_SERVING_PHASE). Declared in the cmd-3 health body
        # (and echoed by cmd 5) so the registry can pool replicas; an
        # attached decode engine's own phase wins when none is given —
        # the engine's warmup ladder is the thing the phase shapes.
        if phase is None:
            phase = (getattr(decode_engine, "phase", None)
                     or os.environ.get("PADDLE_TPU_SERVING_PHASE")
                     or "both")
        if phase not in wire_spec.REPLICA_PHASES:
            raise ValueError(
                f"unknown replica phase {phase!r} (expected one of "
                f"{wire_spec.REPLICA_PHASES})")
        self.phase = phase
        # own_engine: this server is the engine's only handle (serve_model
        # builds one per server) and must close it on stop, or its
        # scheduler thread + compiled programs leak per server lifecycle
        self._own_engine = own_engine and engine is not None
        # continuous-batching decode engine (inference.decode): cmd-1
        # requests carrying the 0x5C field route here and reply as a
        # chunked stream (or a one-shot collected reply)
        self._decode_engine = decode_engine
        self._own_decode_engine = (own_decode_engine
                                   and decode_engine is not None)
        self._decode_stream_timeout = float(os.environ.get(
            "PADDLE_TPU_SERVER_DECODE_TIMEOUT", 300.0))
        self._loader = loader
        self._prefix = prefix
        self._backend_lock = threading.Lock()  # guards _run/_engine swap
        self._reload_lock = threading.Lock()  # one reload at a time
        self._reload_count = 0
        self._max_body = max_body
        self._recv_timeout = recv_timeout
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns = {}  # thread -> {"conn": socket, "busy": bool}
        self._conns_lock = threading.Lock()
        # drain announcement (cmd 8 / begin_drain / stop): while
        # _accepting is False the server still serves everything it
        # receives, but health JSON tells routers to stop sending new
        # work. Guarded by _conns_lock (written from handler threads
        # via cmd 8 and from whoever calls stop()).
        self._accepting = True
        self._draining_deadline = None  # monotonic, or None
        # optional /metrics HTTP endpoint (obs.httpd.MetricsServer),
        # attached by serve_model(metrics_port=...); stop() closes it
        self.metrics_server = None
        self._init_metrics()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _init_metrics(self):
        """Per-server obs instruments, exposed through the process
        registry by a collector (unregistered in stop())."""
        import weakref

        cl = {"port": str(self.port)}
        self._m_conns = obs_metrics.Counter(
            "paddle_server_connections_total",
            "Accepted client connections", const_labels=cl)
        self._m_frames = obs_metrics.Counter(
            "paddle_server_frames_total",
            "Request frames received, by wire command",
            labelnames=("cmd",), const_labels=cl)
        self._m_responses = obs_metrics.Counter(
            "paddle_server_responses_total",
            "cmd-1 infer responses, by wire status "
            "(0 ok, 1 error, 2 retryable)",
            labelnames=("status",), const_labels=cl)
        self._m_reloads = obs_metrics.Counter(
            "paddle_server_reloads_total",
            "Hot model reloads", const_labels=cl)
        self._m_open = obs_metrics.Gauge(
            "paddle_server_connections_open",
            "Currently-connected clients", const_labels=cl)
        self._m_chunks = obs_metrics.Counter(
            "paddle_server_stream_chunks_total",
            "Streaming decode reply frames sent (status 3 + terminal)",
            const_labels=cl)
        self._server_instruments = [
            self._m_conns, self._m_frames, self._m_responses,
            self._m_reloads, self._m_open, self._m_chunks]
        ref = weakref.ref(self)

        def _collector():
            srv = ref()
            if srv is None:
                return None  # GC'd server: registry auto-unregisters
            with srv._conns_lock:
                srv._m_open.set(len(srv._conns))
            return [m.collect() for m in srv._server_instruments]

        self._obs_collector = _collector
        obs_metrics.REGISTRY.register_collector(_collector)

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._m_conns.inc()
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            with self._conns_lock:
                self._conns[t] = {"conn": conn, "busy": False}
            t.start()

    def _set_busy(self, busy):
        with self._conns_lock:
            ent = self._conns.get(threading.current_thread())
            if ent is not None:
                ent["busy"] = busy

    def _backend(self):
        with self._backend_lock:
            return self._run, self._engine

    def _stats_json(self):
        """Body of the `stats` wire command (cmd 5). Shape: the
        batching-engine counters at top level (as always), plus a
        ``decode`` key when a decode engine is attached."""
        _, engine = self._backend()
        stats = {"engine": None} if engine is None else engine.stats()
        stats = dict(stats)
        stats["phase"] = self.phase
        if self._decode_engine is not None:
            stats["decode"] = self._decode_engine.stats()
        return json.dumps(stats)

    def _health_json(self):
        """Body of the `health` wire command (cmd 3): liveness (is the
        serving path able to make progress) and readiness (is it
        accepting work) in one probe."""
        _, engine = self._backend()
        eng = engine.health() if engine is not None else None
        dec = (self._decode_engine.health()
               if self._decode_engine is not None else None)
        with self._conns_lock:
            conns = len(self._conns)
            accepting = self._accepting and not self._stop.is_set()
            dl = self._draining_deadline
        draining = not accepting
        ok = (not draining and (eng is None or eng["ok"])
              and (dec is None or dec["ok"]))
        return json.dumps({
            "ok": ok,
            "phase": self.phase,
            "decode": dec,
            "draining": draining,
            # readiness split (backward-compatible: absent fields mean
            # accepting): a router distinguishes "draining, stop
            # routing but in-flight work finishes" from "dead"
            "accepting": accepting,
            "draining_deadline_s": (None if (accepting or dl is None)
                                    else round(max(0.0,
                                                   dl - time.monotonic()),
                                               3)),
            "connections": conns,
            "reloads": self._reload_count,
            "engine": eng,
        })

    def begin_drain(self, deadline_s=None):
        """Announce a drain (the `drain` wire command, cmd 8): health
        flips to accepting=false so routers stop sending new work, but
        the server keeps serving whatever arrives — the zero-drop half
        of a scale-down or router-orchestrated reload. ``deadline_s``
        is advisory (exported as ``draining_deadline_s``); a negative
        value cancels the drain and re-opens admission."""
        with self._conns_lock:
            if deadline_s is not None and deadline_s < 0:
                self._accepting = True
                self._draining_deadline = None
            else:
                self._accepting = False
                self._draining_deadline = (
                    None if deadline_s is None
                    else time.monotonic() + float(deadline_s))

    # ------------------------------------------------------------- reload
    def reload(self, prefix=None):
        """Atomic hot weight swap (the `reload` wire command, cmd 4).

        Load + warm the new model off to the side (requests keep being
        served by the old one the whole time), swap the (run_fn, engine)
        pair under the backend lock, then close the old engine — which
        drains its in-flight batches. Declared buckets of the old engine
        are precompiled on the new one BEFORE the swap, so post-swap
        traffic never pays a cold compile for them."""
        if self._loader is None:
            raise RuntimeError(
                "this server has no model loader; hot reload needs a "
                "server constructed by serve_model(...) (a bare "
                "PredictorServer wraps an opaque callable)")
        with self._reload_lock:
            if self._stop.is_set():
                # stop() closes the serving engine; a reload racing past
                # it would swap in a fresh engine (scheduler + watchdog
                # + compiled programs) that nothing ever closes
                raise RuntimeError("server is stopping; reload refused")
            new_prefix = prefix or self._prefix
            old_engine = self._backend()[1]
            new_run, new_engine = self._loader(new_prefix)
            warmed = []
            try:
                if new_engine is not None:
                    declared = (old_engine.declared_buckets()
                                if old_engine is not None else None)
                    # warm the same buckets the old engine declared (or
                    # the full power-of-2 ladder) before any request can
                    # see the new engine. The reload lock is dedicated
                    # (one reload at a time) and requests keep flowing
                    # under _backend_lock the whole time — holding it
                    # across the multi-second warmup stalls nobody.
                    warmed = new_engine.warmup(declared or None)  # tpu-lint: disable=TPU302  # dedicated coarse lock; serving path never takes it
                with self._backend_lock:
                    if self._stop.is_set():
                        # stop() closed the serving engine while we were
                        # loading; swapping now would hand the server an
                        # engine nothing ever closes
                        raise RuntimeError(
                            "server stopped during reload; new model "
                            "discarded")
                    old_run, old_engine = self._run, self._engine
                    old_owned = self._own_engine
                    self._run, self._engine = new_run, new_engine
                    self._own_engine = new_engine is not None
                    self._prefix = new_prefix
                    self._reload_count += 1
                    self._m_reloads.inc()
            except BaseException:
                # a failed load/warmup (or a stop racing us) must not
                # leak the freshly built engine's scheduler + watchdog
                # threads and compiled programs
                if new_engine is not None:
                    new_engine.close()
                raise
            if old_engine is not None and old_owned:
                # drains: pending groups on the old engine still fire
                old_engine.close()
            return {"reloaded": True, "prefix": new_prefix,
                    "warm_buckets": list(warmed),
                    "reloads": self._reload_count}

    # ------------------------------------------------------------ handler
    def _infer(self, inputs, budget, trace_id):
        """Run one NON-STREAMING cmd-1 infer request (already parsed);
        returns the encoded response frame body (status + payload)."""
        deadline = (None if budget is None
                    else time.monotonic() + budget)
        t0 = time.perf_counter()
        if budget is not None and budget <= 0.0:
            # the client's budget was spent before the frame finished
            # arriving: drop before dispatch, spend no compute
            return struct.pack("<B", STATUS_OVERLOADED)
        for attempt in (0, 1):
            run, engine = self._backend()
            try:
                if engine is not None:
                    outputs = engine.infer(inputs, deadline=deadline,
                                           trace_id=trace_id)
                else:
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        return struct.pack("<B", STATUS_OVERLOADED)
                    outputs = run(*inputs)
                break
            except EngineClosed:
                # the engine was hot-swapped between our snapshot and
                # the submit: retry once on the new backend so a reload
                # never drops a request
                if attempt:
                    raise
        if not isinstance(outputs, (list, tuple)):
            outputs = [outputs]
        outputs = [np.asarray(o._value if hasattr(o, "_value")
                              else o) for o in outputs]
        enc = _encode_arrays(outputs)
        if trace_id is not None:
            # the handler-side span: decode -> dispatch -> encode (the
            # engine's serving.request span nests inside this window)
            obs_tracing.record_span(
                "serving.reply", time.perf_counter() - t0,
                trace_id=trace_id, port=self.port)
        return struct.pack("<B", STATUS_OK) + enc

    # ------------------------------------------------- streaming decode
    def _send_frame(self, conn, status, payload=b""):
        conn.sendall(struct.pack("<IB", 1 + len(payload), status)
                     + payload)
        self._m_chunks.inc()

    def _serve_decode(self, conn, inputs, budget, trace_id, opts):
        """One cmd-1 decode request (0x5C field present): submit to
        the decode engine and reply as a chunk stream (or a single
        collected reply in one-shot mode). Sends its own frames;
        counts the TERMINAL status in the response counter.

        If the client vanishes mid-stream (sendall fails) the request
        is cancelled so its KV slot frees immediately — a dead reader
        must never ride the batch to max_new_tokens against the slot
        cap (the ISSUE 12 slot-leak audit)."""
        dec = self._decode_engine
        if dec is None or not inputs:
            self._m_responses.inc(status=str(STATUS_ERROR))
            enc = b"no decode engine attached to this server"
            conn.sendall(struct.pack("<IB", 1 + len(enc), STATUS_ERROR)
                         + enc)
            return
        t0 = time.perf_counter()
        if opts.get("handoff"):
            self._serve_prefill_handoff(conn, dec, inputs, budget,
                                        trace_id, t0)
            return
        try:
            req = dec.submit(inputs[0], features=list(inputs[1:]),
                             max_new_tokens=opts.get("max_new_tokens"),
                             token_budget_s=budget, trace_id=trace_id,
                             snapshot_every=opts.get("snapshot_every")
                             or None,
                             speculative=bool(opts.get("speculative")))
        except (RetryableError, EngineClosed):
            self._m_responses.inc(status=str(STATUS_OVERLOADED))
            conn.sendall(struct.pack("<IB", 1, STATUS_OVERLOADED))
            return
        except Exception:  # noqa: BLE001 - bad request (shape/dtype)
            self._m_responses.inc(status=str(STATUS_ERROR))
            conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
            return
        if opts.get("oneshot"):
            try:
                tokens = req.result(timeout=self._decode_stream_timeout)
            except (RetryableError, EngineClosed, TimeoutError):
                dec.cancel(req)
                self._m_responses.inc(status=str(STATUS_OVERLOADED))
                conn.sendall(struct.pack("<IB", 1, STATUS_OVERLOADED))
                return
            except Exception:  # noqa: BLE001 - protocol error status
                dec.cancel(req)
                self._m_responses.inc(status=str(STATUS_ERROR))
                conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                return
            enc = _encode_arrays([tokens])
            self._m_responses.inc(status=str(STATUS_OK))
            conn.sendall(struct.pack("<I", 1 + len(enc))
                         + struct.pack("<B", STATUS_OK) + enc)
            if trace_id is not None:
                obs_tracing.record_span(
                    "serving.reply", time.perf_counter() - t0,
                    trace_id=trace_id, port=self.port,
                    tokens=int(tokens.size))
            return
        # chunk stream: one frame per available token batch
        self._stream_tokens(
            conn, dec, req, t0, trace_id,
            emit_snapshots=bool(opts.get("snapshot_every")))

    def _serve_prefill_handoff(self, conn, dec, inputs, budget,
                               trace_id, t0):
        """cmd-1 with the 0x5C prefill-handoff bit: the disaggregated
        fleet's prefill leg. Runs ONLY the prefill step — the request
        is forced to max_new_tokens=1 with snapshot cadence 1 so the
        engine assembles the n_generated=1 block at the prefill
        boundary — and replies deterministically with exactly two
        frames: one status-3 kv-snapshot frame, then the terminal
        status-0 frame carrying the first token. The router holds the
        block, forwards the token, and seeds a decode replica over
        kv_put/kv_resume. A replica that cannot produce the block
        answers status 2 (retryable) so the leg re-runs elsewhere —
        never a torn stream, never silent token loss."""
        try:
            req = dec.submit(inputs[0], features=list(inputs[1:]),
                             max_new_tokens=1, token_budget_s=budget,
                             trace_id=trace_id, snapshot_every=1)
        except (RetryableError, EngineClosed):
            self._m_responses.inc(status=str(STATUS_OVERLOADED))
            self._send_frame(conn, STATUS_OVERLOADED)
            return
        except Exception:  # noqa: BLE001 - bad request (shape/dtype)
            self._m_responses.inc(status=str(STATUS_ERROR))
            self._send_frame(conn, STATUS_ERROR)
            return
        try:
            tokens = req.result(timeout=self._decode_stream_timeout)
        except (RetryableError, EngineClosed, TimeoutError):
            dec.cancel(req)
            self._m_responses.inc(status=str(STATUS_OVERLOADED))
            self._send_frame(conn, STATUS_OVERLOADED)
            return
        except Exception:  # noqa: BLE001 - protocol error status
            dec.cancel(req)
            self._m_responses.inc(status=str(STATUS_ERROR))
            self._send_frame(conn, STATUS_ERROR)
            return
        blob = req.latest_snapshot()
        if blob is None:
            # the boundary snapshot was dropped (snapshot assembly is
            # degraded-never-fatal): without the block there is nothing
            # to hand off — answer retryable so the router re-runs the
            # prefill elsewhere or degrades to colocated serving
            self._m_responses.inc(status=str(STATUS_OVERLOADED))
            self._send_frame(conn, STATUS_OVERLOADED)
            return
        self._send_frame(conn, STATUS_STREAM, blob)
        self._m_responses.inc(status=str(STATUS_OK))
        self._send_frame(conn, STATUS_OK, _encode_arrays([tokens]))
        if trace_id is not None:
            obs_tracing.record_span(
                "serving.reply", time.perf_counter() - t0,
                trace_id=trace_id, port=self.port,
                tokens=int(tokens.size))

    def _stream_tokens(self, conn, dec, req, t0, trace_id,
                       emit_snapshots=False, sent=0):
        """Drain one decode request onto the wire as a chunk stream
        (status-3 token frames, one terminal frame) — shared by a
        streaming cmd-1 decode reply and a cmd kv_resume reply.

        With ``emit_snapshots`` (the request carried a snapshot
        cadence), each freshly-taken kv-snapshot block goes out as an
        EXTRA status-3 frame — but only once every token it covers is
        already on the wire (``sent`` >= its n_generated), so a
        consumer holding the newest snapshot has always fully
        delivered its position (the router's dedup arithmetic depends
        on exactly this ordering). ``sent`` starts at the snapshot
        position for a resumed stream: snapshot n_generated counts
        from the start of the sequence.

        If the client vanishes mid-stream (sendall fails) the request
        is cancelled so its KV slot frees immediately — a dead reader
        must never ride the batch to max_new_tokens against the slot
        cap (the ISSUE 12 slot-leak audit)."""
        pending = None
        try:
            while True:
                try:
                    toks, done = req.next_tokens(
                        timeout=self._decode_stream_timeout)
                except (RetryableError, EngineClosed, TimeoutError):
                    dec.cancel(req)
                    self._m_responses.inc(status=str(STATUS_OVERLOADED))
                    self._send_frame(conn, STATUS_OVERLOADED)
                    return
                except Exception:  # noqa: BLE001 - protocol error status
                    dec.cancel(req)
                    self._m_responses.inc(status=str(STATUS_ERROR))
                    self._send_frame(conn, STATUS_ERROR)
                    return
                arr = np.asarray(toks, dtype=req.token_dtype)
                sent += arr.size
                if done:
                    self._m_responses.inc(status=str(STATUS_OK))
                    self._send_frame(conn, STATUS_OK,
                                     _encode_arrays([arr]))
                    if trace_id is not None:
                        obs_tracing.record_span(
                            "serving.reply", time.perf_counter() - t0,
                            trace_id=trace_id, port=self.port,
                            tokens=sent)
                    return
                self._send_frame(conn, STATUS_STREAM,
                                 _encode_arrays([arr]))
                if emit_snapshots:
                    got = req.take_snapshot()
                    if got is not None:
                        pending = got
                    if pending is not None and pending[1] <= sent:
                        self._send_frame(conn, STATUS_STREAM, pending[0])
                        pending = None
        except (OSError, ConnectionError):
            # the reader is gone mid-stream: free the KV slot NOW
            dec.cancel(req)
            raise

    def _serve_kv_put(self, conn, payload):
        """cmd kv_put: snapshot preflight against THIS replica
        (``DecodeEngine.seed_check`` — the identity validation shared
        with the resume path, so acceptance here can never drift from
        what a resume actually demands, PLUS a fresh-slot capacity
        check: a prefill->decode handoff seeds a NEW sequence here, so
        a replica that cannot admit one now refuses retryable instead
        of absorbing it). status 0 echoes the JSON header; a refusal
        is status 2; a malformed block is status 1."""
        dec = self._decode_engine
        if dec is None:
            self._m_responses.inc(status=str(STATUS_ERROR))
            enc = b"no decode engine attached to this server"
            conn.sendall(struct.pack("<IB", 1 + len(enc), STATUS_ERROR)
                         + enc)
            return
        try:
            header, _ = dec.seed_check(payload)
        except (RetryableError, EngineClosed) as e:
            self._m_responses.inc(status=str(STATUS_OVERLOADED))
            enc = str(e).encode("utf-8", errors="replace")
            conn.sendall(struct.pack("<IB", 1 + len(enc),
                                     STATUS_OVERLOADED) + enc)
            return
        except Exception as e:  # noqa: BLE001 - malformed block
            self._m_responses.inc(status=str(STATUS_ERROR))
            enc = str(e).encode("utf-8", errors="replace")
            conn.sendall(struct.pack("<IB", 1 + len(enc), STATUS_ERROR)
                         + enc)
            return
        enc = json.dumps(header, sort_keys=True).encode("utf-8")
        self._m_responses.inc(status=str(STATUS_OK))
        conn.sendall(struct.pack("<IB", 1 + len(enc), STATUS_OK) + enc)

    def _serve_kv_resume(self, conn, payload):
        """cmd kv_resume: restore a snapshotted sequence on this
        replica's decode engine and stream its continuation. The reply
        shape is EXACTLY a streaming cmd-1 decode reply (status-3
        chunks carrying only tokens AFTER the snapshot position, one
        terminal frame), so the router's relay loop handles both
        identically; an identity skew is a status-2 terminal."""
        dec = self._decode_engine
        if dec is None:
            self._m_responses.inc(status=str(STATUS_ERROR))
            enc = b"no decode engine attached to this server"
            conn.sendall(struct.pack("<IB", 1 + len(enc), STATUS_ERROR)
                         + enc)
            return
        t0 = time.perf_counter()
        try:
            (header, _arrays, budget, trace_id, opts,
             snap_end) = wire_spec.decode_kv_resume(payload)
        except Exception:  # noqa: BLE001 - malformed body
            self._m_responses.inc(status=str(STATUS_ERROR))
            conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
            return
        opts = opts or {}
        try:
            req = dec.resume(payload[:snap_end], token_budget_s=budget,
                             speculative=bool(opts.get("speculative")),
                             trace_id=trace_id,
                             snapshot_every=opts.get("snapshot_every"),
                             max_new_tokens=opts.get("max_new_tokens"))
        except (RetryableError, EngineClosed):
            # identity/capacity skew or shed: the snapshot may resume
            # elsewhere — a refusal is ALWAYS a status-2 terminal,
            # never silent wrong tokens
            self._m_responses.inc(status=str(STATUS_OVERLOADED))
            self._send_frame(conn, STATUS_OVERLOADED)
            return
        except Exception:  # noqa: BLE001 - inconsistent block
            self._m_responses.inc(status=str(STATUS_ERROR))
            self._send_frame(conn, STATUS_ERROR)
            return
        if opts.get("oneshot"):
            # collect-the-rest mode: one reply with the FULL sequence
            try:
                tokens = req.result(timeout=self._decode_stream_timeout)
            except (RetryableError, EngineClosed, TimeoutError):
                dec.cancel(req)
                self._m_responses.inc(status=str(STATUS_OVERLOADED))
                self._send_frame(conn, STATUS_OVERLOADED)
                return
            except Exception:  # noqa: BLE001 - protocol error status
                dec.cancel(req)
                self._m_responses.inc(status=str(STATUS_ERROR))
                self._send_frame(conn, STATUS_ERROR)
                return
            enc = _encode_arrays([tokens])
            self._m_responses.inc(status=str(STATUS_OK))
            conn.sendall(struct.pack("<I", 1 + len(enc))
                         + struct.pack("<B", STATUS_OK) + enc)
            return
        self._stream_tokens(
            conn, dec, req, t0, trace_id,
            emit_snapshots=bool(opts.get("snapshot_every")),
            sent=int(header["n_generated"]))

    def _handle(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                # idle between frames: block without timeout — keep-alive
                # connections may sit quiet for minutes (stop() unblocks
                # this recv by closing the socket). Once the first header
                # byte arrives, a frame is in flight: a peer that stalls
                # mid-frame times out instead of pinning this thread.
                conn.settimeout(None)
                first = conn.recv(1)
                if not first:
                    raise ConnectionError("peer closed")
                conn.settimeout(self._recv_timeout)
                (blen,) = struct.unpack("<I", first + _read_all(conn, 3))
                if blen == 0:
                    # malformed (a body always has at least the cmd
                    # byte) but the stream is still in sync: report and
                    # keep serving
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    continue
                self._set_busy(True)  # a frame is in flight: drain waits
                try:
                    body = _read_all(conn, blen, limit=self._max_body)
                except BodyTooLarge:
                    # cap exceeded: error status, then close — the rest
                    # of the oversized frame is unread, so the stream
                    # cannot be resynced
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    return
                cmd = body[0]
                self._m_frames.inc(cmd=str(cmd))
                if cmd == CMD_STOP:
                    conn.sendall(struct.pack("<IB", 1, STATUS_OK))
                    threading.Thread(target=self.stop, daemon=True).start()
                    return
                if cmd == CMD_HEALTH:
                    enc = self._health_json().encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    self._set_busy(False)
                    continue
                if cmd == CMD_METRICS:
                    enc = obs_prometheus.render().encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    self._set_busy(False)
                    continue
                if cmd == CMD_RELOAD:
                    prefix = body[1:].decode("utf-8", errors="replace")
                    try:
                        info = self.reload(prefix or None)
                        enc = json.dumps(info).encode("utf-8")
                        conn.sendall(struct.pack("<IB", 1 + len(enc),
                                                 STATUS_OK) + enc)
                    except Exception as e:  # noqa: BLE001 - wire error
                        enc = str(e).encode("utf-8", errors="replace")
                        conn.sendall(struct.pack("<IB", 1 + len(enc),
                                                 STATUS_ERROR) + enc)
                    self._set_busy(False)
                    continue
                if cmd == CMD_STATS:
                    enc = self._stats_json().encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    self._set_busy(False)
                    continue
                if cmd == CMD_DRAIN:
                    deadline_s = (struct.unpack("<d", body[1:9])[0]
                                  if len(body) >= 9 else None)
                    self.begin_drain(deadline_s)
                    enc = self._health_json().encode("utf-8")
                    conn.sendall(struct.pack("<IB", 1 + len(enc),
                                             STATUS_OK) + enc)
                    self._set_busy(False)
                    continue
                if cmd == CMD_KV_PUT:
                    self._serve_kv_put(conn, body[1:])
                    self._set_busy(False)
                    continue
                if cmd == CMD_KV_RESUME:
                    self._serve_kv_resume(conn, body[1:])
                    self._set_busy(False)
                    continue
                if cmd != CMD_INFER:
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    self._set_busy(False)
                    continue
                try:
                    parsed = _decode_request(body[1:])
                except Exception:  # noqa: BLE001 - malformed body
                    self._m_responses.inc(status=str(STATUS_ERROR))
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                    self._set_busy(False)
                    continue
                if parsed[3] is not None:
                    # decode request (0x5C field): chunked streaming
                    # reply (or one-shot collect) — sends its own frames
                    self._serve_decode(conn, parsed[0], parsed[1],
                                       parsed[2], parsed[3])
                    self._set_busy(False)
                    continue
                try:
                    resp = self._infer(parsed[0], parsed[1], parsed[2])
                    self._m_responses.inc(status=str(resp[0]))
                    conn.sendall(struct.pack("<I", len(resp)) + resp)
                except (RetryableError, EngineClosed):
                    # load shed / quarantined bucket / scheduler restart
                    # / expired deadline: a fast, explicit rejection the
                    # client can retry — never an unbounded queue, never
                    # a hang. EngineClosed (a request racing back-to-back
                    # reloads or a stop past _infer's one retry) is
                    # equally transient: the next attempt lands on the
                    # swapped-in engine or a cleanly-restarted server.
                    self._m_responses.inc(status=str(STATUS_OVERLOADED))
                    conn.sendall(struct.pack("<IB", 1, STATUS_OVERLOADED))
                except Exception:  # noqa: BLE001 - protocol error status
                    self._m_responses.inc(status=str(STATUS_ERROR))
                    conn.sendall(struct.pack("<IB", 1, STATUS_ERROR))
                self._set_busy(False)
        except socket.timeout:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.pop(threading.current_thread(), None)

    # tpu-resource: releases=router_socket
    def stop(self, drain=True, timeout=DRAIN_TIMEOUT):
        """Graceful shutdown: stop accepting, let requests that are
        mid-processing finish (up to `timeout`), force-close idle
        keep-alive connections — a rolling restart neither drops a
        response mid-write nor hangs on a silent client."""
        # the drain announcement first: a health probe that races the
        # shutdown (over an already-open connection) reads
        # accepting=false + the drain budget, not a confusing
        # "ok but about to vanish"
        self.begin_drain(timeout if drain else 0.0)
        self._stop.set()
        obs_metrics.REGISTRY.unregister_collector(self._obs_collector)
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        # a reload mid-flight cannot swap past us: its swap re-checks
        # _stop under _backend_lock (set above, before our engine read
        # below) and aborts, closing its own new engine — so the engine
        # we read here is the one that is actually serving, and stop()
        # never waits out a multi-second model load
        try:
            # shutdown BEFORE close: on Linux, close() alone does not
            # wake a thread already blocked in accept() — the accept
            # loop would park forever and anything join()ing it (a
            # serve-until-stopped wrapper process) would hang with it
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()  # no new connections
        except OSError:
            pass
        with self._backend_lock:
            engine = self._engine if self._own_engine else None
        dec = self._decode_engine if self._own_decode_engine else None
        if not drain:
            if engine is not None:
                engine.close()
            if dec is not None:
                dec.close()
            return
        me = threading.current_thread()
        deadline = time.monotonic() + timeout
        with self._conns_lock:
            entries = [(t, e) for t, e in self._conns.items() if t is not me]
        for t, ent in entries:
            if ent["busy"]:
                t.join(max(0.0, deadline - time.monotonic()))
        # whoever is left is idle (blocked waiting for the next frame) or
        # overran the drain window — unblock by closing the socket
        with self._conns_lock:
            leftover = [e["conn"] for t, e in self._conns.items()
                        if t is not me]
        for c in leftover:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if engine is not None:
            # handlers are drained/unblocked; pending engine requests
            # still fire (close() lets partial batches complete)
            engine.close()
        if dec is not None:
            # streaming handlers were unblocked above; in-flight
            # sequences fail retryable (a stop mid-stream is a shed,
            # never silent truncation)
            dec.close()


def serve_model(path_prefix, port=0, dynamic_batching=False,
                max_batch_size=32, max_wait_ms=2.0, max_queue=256,
                warmup=True, metrics_port=None, quant=None, mesh=None,
                phase=None, **engine_kwargs):
    """Load a jit-saved model and serve it (the C API's server side).

    With ``dynamic_batching=True`` (needs a batch-polymorphic save, see
    jit.save) all connections share one BatchingEngine: requests
    coalesce into padded shape-bucket batches, declared buckets are
    precompiled up front, and saturation sheds as wire status 2. Extra
    ``engine_kwargs`` (breaker_threshold, watchdog_interval,
    artifact_store, ...) pass through to the BatchingEngine.

    With ``PADDLE_TPU_ARTIFACT_DIR`` set (or an explicit
    ``artifact_store=``), warmup — including the off-to-the-side warmup
    a hot reload performs — loads each bucket's program from the
    persistent compiled-artifact store instead of compiling: a fresh
    replica process reaches its first healthy reply with zero XLA
    compiles once any replica has published the ladder
    (``bench.py coldstart`` measures exactly this), and a corrupt or
    stale store entry silently degrades that bucket to an inline
    compile (README "Artifact store" has the degradation matrix).

    ``metrics_port`` (0 = any free port) additionally serves the
    Prometheus text exposition of the process obs registry on
    ``http://host:metrics_port/metrics`` — the scrape-friendly twin of
    the ``metrics`` wire command (cmd 6). The endpoint lives and dies
    with the server (``server.metrics_server.port`` has the bound
    port).

    ``quant`` (env default ``PADDLE_TPU_SERVING_QUANT``) declares the
    serving quantization mode this replica MUST serve (``"f32"`` |
    ``"w8"`` | ``"w8a8"`` | ``"bf16w"``): the loaded model's recorded
    mode (jit.save's ``quant=`` sidecar field) is checked at load time
    — and on every hot reload — so a fleet flipped to w8 can never
    silently serve an f32 save (or vice versa). Unset = serve whatever
    the save recorded.

    ``mesh`` (env default ``PADDLE_TPU_SERVING_MESH``) declares the
    serving mesh this replica shards its weights over (``"single"`` |
    ``"tp<k>"`` | ``"fsdp<m>"`` | ``"fsdp<m>xtp<k>"``; README "Sharded
    serving"). Sharded serving runs through the batching engine
    (``dynamic_batching=True``): weights are committed to the mesh once
    at load and every bucket program is a per-(bucket, mesh) pjit
    program with its own artifact-store identity — wire-transparent to
    all four clients. A save that recorded an intended mesh
    (``jit.save(..., mesh=...)``) is checked against the declared one
    at load time AND on every hot reload; the mesh resolved at first
    load is pinned, so a reload can never silently flip a replica's
    topology. Unset = serve whatever the save recorded (or
    single-chip).

    ``phase`` (env default ``PADDLE_TPU_SERVING_PHASE``) declares the
    replica's pool in a disaggregated prefill/decode fleet
    (``"prefill"`` | ``"decode"`` | ``"both"``; README "Disaggregated
    serving"): reported in the cmd-3 health body so a phase-pooled
    ``Fleet`` routes prompt ingestion and token generation to the
    right pool. Placement only — the replica still serves every
    command, so a fleet whose other pool collapsed degrades to
    colocated serving here.

    The returned server supports the ``reload`` wire command (cmd 4):
    re-save the model to the same (or a new) prefix and issue a reload
    to hot-swap weights with zero dropped requests."""
    from ..jit import load as jit_load
    from .sharding import SINGLE, ServingMesh

    if quant is None:
        quant = os.environ.get("PADDLE_TPU_SERVING_QUANT") or None
    if quant not in (None, "f32"):
        # fail at entry with the valid mode set — a typo'd deployment
        # knob ('W8', 'int8') must not surface later as a misleading
        # "re-save your model" mismatch error
        from ..quantization.serving import check_mode

        check_mode(quant)
    if mesh is None:
        mesh = os.environ.get("PADDLE_TPU_SERVING_MESH") or None
    # fail at entry with the valid descriptor grammar — same rationale
    # as the quant knob (a typo'd mesh must not surface as a
    # misleading save-mismatch error later)
    declared_mesh = (None if mesh is None
                     else ServingMesh.parse(mesh).descriptor)
    # the mesh resolved at FIRST load is pinned for the server's
    # lifetime: hot reload checks the new save against it, so a reload
    # can change weights, never the replica's topology
    pinned_mesh = {}

    def loader(prefix):
        layer = jit_load(prefix)
        if quant is not None:
            have = getattr(layer, "_quant_mode", None) or "f32"
            if have != quant:
                raise ValueError(
                    f"{prefix}: saved quant mode {have!r} does not "
                    f"match the declared serving mode {quant!r} "
                    "(PADDLE_TPU_SERVING_QUANT / serve_model(quant=)); "
                    "re-save with jit.save(..., quant=...) or fix the "
                    "deployment knob")
        recorded_mesh = getattr(layer, "_serving_mesh", None)
        want = (declared_mesh if declared_mesh is not None
                else pinned_mesh.get("desc"))
        if (want is not None and recorded_mesh is not None
                and recorded_mesh != want):
            raise ValueError(
                f"{prefix}: saved serving mesh {recorded_mesh!r} does "
                f"not match the declared mesh {want!r} "
                "(PADDLE_TPU_SERVING_MESH / serve_model(mesh=)); "
                "re-save with jit.save(..., mesh=...) or fix the "
                "deployment knob")
        eff_mesh = want or recorded_mesh or SINGLE
        pinned_mesh.setdefault("desc", eff_mesh)
        if eff_mesh != SINGLE and not dynamic_batching:
            raise ValueError(
                f"serving mesh {eff_mesh!r} needs the batching engine "
                "(the per-bucket pjit programs live there): pass "
                "dynamic_batching=True to serve_model")

        def run(*arrays):
            out = layer(*arrays)
            return out if isinstance(out, (list, tuple)) else [out]

        engine = None
        if dynamic_batching:
            from .batching import BatchingEngine

            engine = BatchingEngine.for_layer(
                layer, max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms, max_queue=max_queue,
                mesh=eff_mesh, **engine_kwargs)
        return run, engine

    run, engine = loader(path_prefix)
    if engine is not None and warmup:
        engine.warmup()
    server = PredictorServer(run, port=port, engine=engine,
                             own_engine=engine is not None,
                             loader=loader, prefix=path_prefix,
                             phase=phase)
    if metrics_port is not None:
        from ..obs.httpd import MetricsServer

        try:
            server.metrics_server = MetricsServer(metrics_port)
        except BaseException:
            server.stop(drain=False)
            raise
    return server
