"""Serving mesh layer: shard a served model's weights across chips
once at load, and name the layout so compiled programs key on it.

The serving stack (batching.py one-shot engine, decode.py continuous
batching) ran every model on ONE chip: models bigger than one chip's
HBM — the Llama-7B+ scenario the decode engine points at — could not
be served at all. The training side already proves the meshes work
(``distributed/topology.build_mesh`` + ``distributed/spmd.py``'s
PartitionSpec discipline, green over gloo CPU collectives in the
MULTICHIP dryruns); this module is the thin serving-side counterpart:

- :class:`ServingMesh` — a CANONICAL mesh descriptor (``"single"``,
  ``"tp2"``, ``"fsdp2"``, ``"fsdp2xtp2"``) parsed from
  ``serve_model(mesh=...)`` / ``DecodeEngine(mesh=...)`` or the
  ``PADDLE_TPU_SERVING_MESH`` env knob. The descriptor string IS the
  artifact-store key component (``ArtifactKey.mesh``): a sharded
  export can never satisfy a single-chip key and vice versa, and
  sharded programs persist / single-flight / cold-start across a
  replica fleet exactly like f32 and quantized ones.
- **Axes** (the SpecLayout fsdp×tp discipline, SNIPPETS [2], mapped
  onto ``topology.build_mesh``): ``tp`` (tensor parallel, the
  topology's ``mp`` axis — innermost, highest-bandwidth ICI ring)
  shards every weight's LAST dim; ``fsdp`` (the topology's
  ``sharding`` axis) shards the FIRST dim of >= 2-D weights. A dim
  that does not divide stays replicated — the discipline degrades
  per-tensor, never refuses a model.
- **Shard once at load**: :meth:`shard_arrays` commits the resident
  weights to the mesh with ``jax.device_put``; per-bucket programs
  are then compiled with those shardings as ``in_shardings`` (weights
  stay runtime args, shared across buckets, exactly like the
  single-chip engines) and replicated batch inputs/outputs, so the
  wire protocol is untouched — sharding is invisible to all four
  clients.

Determinism contract (measured on this jaxlib, pinned by
tests/test_sharded_serving.py): a program whose sharded dims are all
OUTPUT dims (the tp discipline on feed-forward layers) is **bitwise
identical** to its single-chip twin — each output element is computed
whole on one device and concatenated exactly. Sharding a CONTRACTION
dim (fsdp on a weight's first dim, or tp feeding an attention
contraction) makes XLA insert a psum whose reduction order differs
from the single-chip gemm: replies then agree within
:data:`SHARDED_FLOAT_TOL` (measured ~1e-6 relative on this jaxlib),
never bitwise. Solo-vs-batch decode determinism is bitwise PER MESH
regardless: row independence and masked-attention padding stability
survive sharding because every device sees whole rows.

The descriptor grammar is deliberately tiny and closed: new axes
(``pp``, ``ep``, ``sp`` serving) must extend :data:`_DESCRIPTOR_RE`
and the canonical ordering here, in ONE place, or the artifact store
would silently fork identities ("tp2xfsdp2" vs "fsdp2xtp2").
"""
import os
import re

SINGLE = "single"

# relative tolerance for sharded-vs-single float replies when a
# contraction dim is sharded (psum reduction-order drift; measured
# ~1e-6 on this jaxlib — the bound is deliberately 10x the observation)
SHARDED_FLOAT_TOL = 1e-5

# canonical axis order in descriptors: fsdp (the topology 'sharding'
# axis) before tp (the topology 'mp' axis)
_DESCRIPTOR_RE = re.compile(r"^(?:fsdp(?P<fsdp>[0-9]+))?"
                            r"(?:x?tp(?P<tp>[0-9]+))?$")
# accepted aliases for the tp axis (the reference's model-parallel
# serving expectation spells it mp)
_ALIAS_RE = re.compile(r"^mp(?P<tp>[0-9]+)$")


class ServingMesh:
    """One serving mesh: fsdp x tp shard counts plus the lazily-built
    jax Mesh. Immutable after construction; the canonical
    ``descriptor`` string is its identity everywhere (artifact keys,
    metrics labels, health/stats, ledger events, the wire's cmd-3/5
    JSON)."""

    __slots__ = ("fsdp", "tp", "_mesh")

    def __init__(self, fsdp=1, tp=1):
        fsdp, tp = int(fsdp), int(tp)
        if fsdp < 1 or tp < 1:
            raise ValueError(
                f"mesh axis sizes must be >= 1 (got fsdp={fsdp}, tp={tp})")
        self.fsdp = fsdp
        self.tp = tp
        self._mesh = None

    # ------------------------------------------------------- identity
    @property
    def descriptor(self):
        """Canonical string form — the ``ArtifactKey.mesh`` value."""
        if self.is_single:
            return SINGLE
        parts = []
        if self.fsdp > 1:
            parts.append(f"fsdp{self.fsdp}")
        if self.tp > 1:
            parts.append(f"tp{self.tp}")
        return "x".join(parts)

    @property
    def is_single(self):
        return self.fsdp == 1 and self.tp == 1

    @property
    def n_shards(self):
        """Devices this mesh spans (the exported program's device
        count — :func:`check_nr_devices` gates store loads on it)."""
        return self.fsdp * self.tp

    def __repr__(self):
        return f"ServingMesh({self.descriptor!r})"

    def __eq__(self, other):
        return (isinstance(other, ServingMesh)
                and other.fsdp == self.fsdp and other.tp == self.tp)

    def __hash__(self):
        return hash((self.fsdp, self.tp))

    # -------------------------------------------------------- parsing
    @classmethod
    def parse(cls, spec):
        """Descriptor -> ServingMesh. Accepts None / ``""`` /
        ``"single"`` (single-chip), ``"tp<k>"``, ``"fsdp<m>"``,
        ``"fsdp<m>xtp<k>"``, the ``"mp<k>"`` alias (normalized to
        ``tp<k>`` — the reference's model-parallel spelling), and a
        ServingMesh (passed through)."""
        if isinstance(spec, ServingMesh):
            return spec
        if spec is None:
            return cls()
        s = str(spec).strip().lower()
        if s in ("", SINGLE, "f32"):  # "f32" guard: a swapped quant/mesh
            if s == "f32":            # knob pair should say so, not parse
                raise ValueError(
                    "'f32' is a quant mode, not a mesh descriptor — did "
                    "you swap PADDLE_TPU_SERVING_QUANT and "
                    "PADDLE_TPU_SERVING_MESH?")
            return cls()
        m = _ALIAS_RE.match(s)
        if m:
            return cls(tp=int(m.group("tp")))
        m = _DESCRIPTOR_RE.match(s)
        if not m or (m.group("fsdp") is None and m.group("tp") is None):
            raise ValueError(
                f"unknown serving mesh descriptor {spec!r}: expected "
                "'single', 'tp<k>', 'mp<k>', 'fsdp<m>' or "
                "'fsdp<m>xtp<k>' (e.g. mesh='tp2', mesh='fsdp2xtp2')")
        return cls(fsdp=int(m.group("fsdp") or 1),
                   tp=int(m.group("tp") or 1))

    # ------------------------------------------------------ jax build
    def build(self):
        """The jax Mesh (lazy, cached). Raises with the remedy when
        the process has fewer devices than the mesh needs — on a CPU
        box that is the ``--xla_force_host_platform_device_count``
        XLA flag, on a TPU pod it is the slice topology."""
        if self._mesh is not None:
            return self._mesh
        if self.is_single:
            raise ValueError("a single-chip mesh has no device Mesh; "
                             "callers must branch on is_single")
        import jax

        have = len(jax.devices())
        if have < self.n_shards:
            raise ValueError(
                f"serving mesh {self.descriptor!r} needs "
                f"{self.n_shards} devices but this process has {have} "
                "(CPU: set XLA_FLAGS=--xla_force_host_platform_device_"
                "count=N before jax initializes; TPU: use a slice with "
                "enough chips)")
        from ..distributed import topology

        # tp -> the topology's innermost 'mp' axis (highest-bandwidth
        # ICI ring, the tensor-parallel placement rule); fsdp -> its
        # 'sharding' axis — the same mapping the training side uses
        self._mesh = topology.build_mesh(sharding=self.fsdp, mp=self.tp)
        return self._mesh

    # ------------------------------------------- PartitionSpec layout
    def param_spec(self, shape):
        """The SpecLayout fsdp x tp discipline for one weight:

        - >= 2-D: first dim over fsdp, last dim over tp (each only
          when it divides — an indivisible dim stays replicated);
        - 1-D: over tp when divisible (bias rides its matmul's
          output-dim layout);
        - 0-D: replicated.

        Returns a ``jax.sharding.PartitionSpec`` over the topology
        axis names (``sharding`` = fsdp, ``mp`` = tp)."""
        from jax.sharding import PartitionSpec as P

        shape = tuple(int(d) for d in shape)
        if not shape:
            return P()
        if len(shape) == 1:
            if self.tp > 1 and shape[0] % self.tp == 0:
                return P("mp")
            return P()
        dims = [None] * len(shape)
        if self.fsdp > 1 and shape[0] % self.fsdp == 0:
            dims[0] = "sharding"
        if self.tp > 1 and shape[-1] % self.tp == 0:
            dims[-1] = "mp"
        return P(*dims)

    def param_sharding(self, shape):
        from jax.sharding import NamedSharding

        return NamedSharding(self.build(), self.param_spec(shape))

    def replicated(self):
        """The sharding of everything that is NOT a weight: batch
        inputs, outputs, KV scratch — replicated, so the wire sees
        identical bytes and the host-side engines stay unchanged."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.build(), P())

    def shard_arrays(self, arrays):
        """Commit weights to the mesh ONCE at load: returns
        ``(placed, shardings)`` where ``placed[i]`` is ``arrays[i]``
        device_put under its discipline sharding. The engines hold
        these as the runtime args every bucket program shares."""
        import jax

        shardings = [self.param_sharding(getattr(a, "shape", ()))
                     for a in arrays]
        return ([jax.device_put(a, s) for a, s in zip(arrays, shardings)],
                shardings)

    def shard_fraction(self, shape):
        """1 / (shards this weight is split across) under the
        discipline — the per-device residency factor."""
        spec = self.param_spec(shape)
        frac = 1.0
        for dim_axes in spec:
            if dim_axes is None:
                continue
            for ax in ((dim_axes,) if isinstance(dim_axes, str)
                       else dim_axes):
                frac /= self.fsdp if ax == "sharding" else self.tp
        return frac

    def per_shard_bytes(self, arrays):
        """Weight bytes RESIDENT PER DEVICE under this mesh — the
        bigger-than-one-chip proxy ``bench.py sharded`` reports (a
        model whose per-shard bytes fit HBM serves even when its total
        bytes do not)."""
        import numpy as np

        total = 0.0
        for a in arrays:
            shape = tuple(getattr(a, "shape", ()))
            nbytes = (getattr(a, "nbytes", None)
                      or int(np.prod(shape or (1,)))
                      * np.dtype(getattr(a, "dtype", np.float32)).itemsize)
            total += nbytes * self.shard_fraction(shape)
        return int(total)


def resolve(mesh=None):
    """One resolution rule for every entry point: explicit arg >
    ``PADDLE_TPU_SERVING_MESH`` env > single-chip. Always returns a
    ServingMesh."""
    if mesh is None:
        mesh = os.environ.get("PADDLE_TPU_SERVING_MESH") or None
    return ServingMesh.parse(mesh)


def check_nr_devices(exported, mesh):
    """Gate a (store-loaded or freshly-built) exported program on its
    recorded device count matching the mesh. The artifact KEY already
    separates meshes, so in the normal flow this never fires — it is
    the defense in depth against a copied/renamed store dir or a
    hand-loaded export: a 4-device program must never reach a
    single-chip call site (where it would fail mid-request, or worse).
    Raises ValueError on skew."""
    want = 1 if mesh is None or mesh.is_single else mesh.n_shards
    got = int(getattr(exported, "nr_devices", 1))
    if got != want:
        desc = SINGLE if mesh is None else mesh.descriptor
        raise ValueError(
            f"mesh skew: exported program spans {got} device(s) but the "
            f"engine's mesh {desc!r} expects {want}")
