"""Continuous-batching autoregressive decode engine (ROADMAP item 1).

The dynamic-batching engine (batching.py) serves ONE-SHOT inference:
a request is a batch of rows, a reply is the whole answer. Token
streaming is a different shape of work — a request is a *sequence*
that produces one token per model step for hundreds of steps, and
sequences finish at wildly different times. Padding a fixed batch to
the slowest member (the one-shot strategy) leaves the chip idle on
every retired row; PERF.md pegs the untuned decode path at 0.2–0.5 of
roofline for exactly this reason. The structural fix is
**iteration-level scheduling** (the continuous-batching design of
Orca/vLLM, and the concurrency lesson of PAPERS.md "Exploring the
limits of Concurrency in ML Training on Google TPUs"): the scheduler
re-forms the running batch EVERY step, so sequences join the moment a
slot frees and leave the moment they finish::

    requests --> bounded queue --> iteration scheduler
                  (shed, purge)        |
                                       v            per-(phase, rows,
      admit joiners ---> PREFILL program             seq) AOT cache
      every iteration     (rows_bucket, prompt_bucket)   |  artifact
                                       |                 |  store keys
      one token/seq  <--- DECODE STEP program  <---------+
      every iteration     (slot_bucket, seq_bucket)
                                       |
      retire on eos/max/deadline; slot freed for the next joiner

**KV slots.** Each running sequence owns a slot of paged host-side
KV-cache storage (:class:`_KVSlots`): per-slot buffers grow in
power-of-2 pages, so memory tracks actual sequence lengths, and each
decode step gathers the active slots into a fixed-shape batch
``[slot_bucket, seq_bucket, ...]`` — the same power-of-2 shape-bucket
machinery the one-shot engine uses, which is what keeps the number of
compiled programs a small ladder instead of one per (batch, length)
pair. Decode-step exports flow through the PR 10 artifact store under
their own keys (phase + seq bucket encoded in the signature), so a
fresh decode replica warms its whole program ladder with zero inline
XLA compiles once any replica has published it.

**Bitwise determinism contract** (verified in tests/test_decode.py):
a sequence decoded inside a continuous batch emits the SAME tokens as
the same sequence decoded solo, under greedy sampling, across
join/leave events and every wire dtype of its feature arrays. This
holds because (a) rows of XLA's row-independent CPU programs are
bitwise stable across batch sizes >= 2 (the PR 4 result; slot buckets
are floored at 2 for exactly this reason), and (b) masked attention
with exact ``-inf`` score masking and post-softmax zeroing is bitwise
stable across KV padding widths — padded positions contribute exact
``0.0`` terms, which pass through XLA's reductions unchanged
(measured on this jaxlib; the model contract below requires that
masking discipline). The engine zero-fills gathered KV beyond each
sequence's length so stale slot contents can never reach a program.

**Model contract** (:class:`DecodeModel`): two pure jax functions
over flat positional args (export-friendly, weights as runtime args):

    prefill_fn(params, tokens[b,p] i32, lengths[b] i32, *feat)
        -> (logits[b, vocab] at each row's LAST valid position,
            *kv[b, p, ...])  — one array per kv_spec entry
    step_fn(params, tokens[b] i32, positions[b] i32,
            *kv[b, s, ...], *feat)
        -> (logits[b, vocab], *new_kv[b, ...])
        The step must write the incoming token's kv at ``positions``
        into its OWN attention (the passed kv buffers are donated
        scratch) and return the new entries for the host to persist.

    Padding rows carry token 0 / length 1 / position 0 / zero kv /
    zero features; the model must produce finite outputs for them
    (mask invalid positions to -inf BEFORE softmax and zero the
    probabilities after, never ``nan``).

**Robustness** is the PR 5 plumbing, unchanged in shape: per-program
circuit breakers (:class:`batching._Breaker`), a scheduler watchdog
(heartbeat per iteration; a dead/wedged scheduler is restarted, the
active sequences fail retryable — wire status 2 — and parked requests
are served by the replacement), bounded queue with
:class:`batching.EngineOverloaded` shedding, and chaos sites
``serving.decode.admit`` / ``serving.decode.prefill`` /
``serving.decode.step``. Deadlines become **per-token SLOs**: a
request's wire budget bounds the time to its FIRST token and every
inter-token gap; a sequence that blows its per-token budget fails
retryable and its KV slot is purged immediately (no slot leak against
the slot cap — chaos-verified at ``serving.decode.step``).

Telemetry: per-token latency and time-to-first-token histograms
(``paddle_decode_ttft_seconds`` / ``paddle_decode_intertoken_seconds``)
are engine-owned obs.metrics instruments exposed through the process
registry (wire cmd 6 / ``/metrics``); traced requests get per-token
``serving.decode.token`` spans in the obs.tracing buffer; every
program materialization lands in the compile ledger under
``decode/...`` labels (what ``bench.py perfproxy``'s decode contract
gates on).

**Stream resume (PR 17).** A running sequence can be checkpointed into
a self-describing *kv-snapshot block* (``wire_spec.encode_kv_snapshot``:
paged KV prefix + prompt + generated-token tail + greedy scalars, under
a versioned header carrying the model fingerprint, weights digest,
quant mode, and mesh descriptor) and resumed on ANY replica of the same
identity via
:meth:`DecodeEngine.resume`, which re-enters the step loop at the exact
sequence position. Greedy decode is RNG-free and the step ladder is
shared, so the resumed suffix is bitwise identical to an unbroken solo
decode — the PR 12 solo-vs-batch contract holds across the migration
boundary. A replica whose identity skews from the header refuses with
:class:`SnapshotRefused` (wire status 2), never silent wrong tokens.
Requests opt in per-sequence (``snapshot_every=N`` — the wire cadence
bits of the 0x5C field); snapshot assembly failures degrade to "no
resume point", never to a failed stream (chaos sites
``serving.decode.snapshot`` / ``serving.decode.resume``).

**KV reuse ladder (PR 19).** Two rungs on top of the substrate above,
both compiled to the same fixed (phase, rows, seq) program ladder —
never data-dependent shapes. (a) *Content-addressed prefix caching*
(:mod:`prefix_cache`): token prefixes hash at page-aligned boundaries
(pages = ``min_seq_bucket`` tokens; chain hashes, so every boundary of
a prompt costs one linear pass) and the KV pages of hot prefixes live
once in the refcounted page pool of :class:`_KVSlots`. A hit installs
the cached pages into a fresh slot by reference — copy-on-write: a
slot writing into a shared page clones it first, and release
decrements, never frees, a page another sequence (or the cache)
holds — so model programs run only over the uncached suffix, fed
token-by-token through the already-warm step rungs. To keep the PR 12
bitwise contract, EVERY emitted first token comes from step-shaped
math: a cold prefill gains one *finishing step* (re-feeding the last
prompt token at its position — the KV row it writes is bitwise equal
to the prefill program's, and its logits are bitwise equal to the
prefill logits on this jaxlib), which is the identical computation the
prefix-hit path's last suffix step performs — hit-vs-cold token
equality holds by construction, not by tolerance. (b) *Speculative
decoding*: a cheap ``DecodeModel.draft`` companion proposes k-1
tokens per iteration and the target verifies all k positions in ONE
batched ``verify`` program — k unrolled step_fn iterations fused in
one jit, bitwise equal per position to k sequential step dispatches —
so greedy accept/reject emits exactly the tokens non-speculative
greedy would (rejected runs roll back by committing only accepted KV
entries; the gathered device buffers are donated scratch). Clients
opt in per request (wire 0x5C bit 61); non-opted streams are
byte-identical.

Env knobs (constructor kwargs override):
    PADDLE_TPU_DECODE_SNAPSHOT_EVERY   default snapshot cadence in
                                       generated tokens (0 = never;
                                       requests override per-sequence)
    PADDLE_TPU_PREFIX_DIR              persistent prefix-cache tier
                                       (artifact-store layout; unset =
                                       in-memory tier only)
    PADDLE_TPU_PREFIX_MAX_BYTES        prefix-cache byte budget
                                       (default 256 MiB)
    PADDLE_TPU_PREFIX_DISABLE          "1" disables prefix caching
    PADDLE_TPU_SPEC_K                  speculative tokens per verify
                                       (k >= 2 enables speculation on
                                       draft-equipped engines; 0 = off)
    PADDLE_TPU_DECODE_MAX_SLOTS        concurrent sequences (default 8)
    PADDLE_TPU_DECODE_MAX_SEQ_LEN      prompt+generated cap (default 256)
    PADDLE_TPU_DECODE_MAX_QUEUE        bounded wait queue (default 64)
    PADDLE_TPU_DECODE_MIN_SEQ_BUCKET   smallest kv/prompt bucket (8)
    PADDLE_TPU_DECODE_MAX_NEW_TOKENS   default per-request cap (64)
    PADDLE_TPU_DECODE_MAX_PROMPT_LEN   admission cap on prompt length
                                       (default max_seq_len)
    PADDLE_TPU_SERVING_MESH            serving mesh descriptor ("tp2",
                                       "fsdp2xtp2"; default single) —
                                       params shard once and the whole
                                       program ladder becomes
                                       per-(bucket, mesh) pjit programs
    (breaker/watchdog knobs: the PADDLE_TPU_SERVING_* family)
"""
import hashlib
import os
import threading
import time
import traceback
import weakref

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from ..obs.ledger import LEDGER
from ..resilience import chaos
from ..resilience.retry import _env_float, _env_int
from ..serialize import artifact_store as _artifacts
from . import sharding as _sharding
from . import wire_spec as _wire_spec
from .prefix_cache import PrefixCache, feature_seed, prefix_hashes
from ..serialize.export import (canonical_module_bytes, deserialize_exported,
                                model_fingerprint, serialize_exported)
from .batching import (BucketQuarantined, DeadlineExceeded, EngineClosed,
                       EngineOverloaded, RetryableError, SchedulerRestarted,
                       _Breaker, bucket_rows, store_backed_compile)

# numpy dtypes the spec admits as decode prompts/token ids (wire codes
# in wire_spec.TOKEN_DTYPE_CODES; the token chunks echo the prompt's
# dtype bit for bit)
_TOKEN_DTYPES = frozenset(_wire_spec.NUMPY_BY_CODE[c]
                          for c in _wire_spec.TOKEN_DTYPE_CODES)


class SnapshotRefused(RetryableError):
    """A kv snapshot does not match this replica's identity
    (fingerprint / quant / mesh / shape contract skew) or cannot fit
    its configured limits. Maps to wire status 2: the stream is
    resumable on a matching replica — refusing is ALWAYS preferable to
    decoding garbage from a foreign KV layout."""

# Machine-checked lock order (tools/tracelint.py --concurrency, TPU309):
# the decode engine lock is a SUBSYSTEM lock like BatchingEngine's —
# obs instrument/registry locks nest strictly inside it, never the
# reverse (exposition must not deadlock the decode loop).
# tpu-lock-order: DecodeEngine._lock < Metric._lock  # subsystem -> instrument
# tpu-lock-order: DecodeEngine._lock < Registry._lock  # collectors run OUTSIDE the registry lock


def seq_bucket(n, min_bucket, max_len):
    """Power-of-2 sequence-length bucket: next pow2 >= n, floored at
    ``min_bucket``, clamped to ``max_len`` (the ladder's top rung)."""
    if n <= 0:
        raise ValueError(f"need length >= 1, got {n}")
    return max(min_bucket, bucket_rows(n, max_len))


class DecodeModel:
    """Adapter holding the prefill/step jax functions, their runtime
    parameters, and the shape contract (see module docstring).

    ``kv_spec`` / ``feature_spec``: tuples of ``(trailing_shape,
    dtype)`` per KV buffer / per-sequence feature array. A KV buffer's
    full shape is ``[rows, seq, *trailing]``; a feature's is
    ``[rows, *trailing]`` (constant per sequence — e.g. a user
    embedding or per-sequence temperature, any wire dtype).

    ``fingerprint``: content identity for the artifact store. Default:
    computed lazily (sha256 of the step program's serialized export at
    a canonical shape — same identity rule as jit.save: the traced
    computation + avals, never the weight values).

    ``quant``: the serving quantization mode the params/functions were
    built under (``quantization.quantize_decode_model`` sets it;
    None = f32). It rides in every program ArtifactKey, ledger event,
    and compile metric, and folds into the lazy fingerprint — a
    quantized decode ladder never collides with the f32 one in the
    artifact store.

    ``draft``: an optional companion DecodeModel for speculative
    decoding — a much cheaper model over the SAME vocab and
    feature_spec (its kv_spec may differ freely). The engine drives it
    through its own program ladder and KV pool; greedy output stays
    bitwise-equal to decoding without it, so a draft can only ever buy
    speed, never change tokens."""

    def __init__(self, params, prefill_fn, step_fn, kv_spec, vocab_size,
                 feature_spec=(), eos_token_id=None, fingerprint=None,
                 quant=None, draft=None):
        self.params = list(params)
        self.prefill_fn = prefill_fn
        self.step_fn = step_fn
        self.kv_spec = tuple((tuple(int(d) for d in tr), np.dtype(dt))
                             for tr, dt in kv_spec)
        self.feature_spec = tuple((tuple(int(d) for d in tr), np.dtype(dt))
                                  for tr, dt in feature_spec)
        self.vocab_size = int(vocab_size)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self._fingerprint = fingerprint
        self.quant = quant
        self.draft = draft


class _Programs:
    """Per-(phase, rows, seq) AOT program cache backend for the decode
    engine — the decode twin of batching.AotLayerRunner. ``compile``
    returns ``(run, source)`` via the shared
    :func:`batching.store_backed_compile` flow, so decode-step exports
    persist in the PR 10 artifact store (own keys: the phase and seq
    bucket ride in the signature) with the same single-flight /
    verify-then-quarantine / degrade-to-inline semantics."""

    def __init__(self, model, store=None, mesh=None, spec_k=0):
        import jax

        self._jax = jax
        self._model = model
        # k for the "verify" phase: one program checks k speculative
        # positions per dispatch — k unrolled step_fn iterations fused
        # in one jit, each reading the KV entries the previous ones
        # wrote. Bitwise equal per position to k sequential step
        # dispatches (measured on this jaxlib: the per-position math
        # is the step program's, only the dispatch boundary moves).
        self._spec_k = int(spec_k)
        self._store = store if store is not None \
            else _artifacts.default_store()
        self._warmup_wait_s = _env_float(
            "PADDLE_TPU_ARTIFACT_WARMUP_WAIT_S", 120.0)
        self._fp_lock = threading.Lock()
        self._weights_digest_cached = None
        # serving mesh: single runs the historical path byte-for-byte;
        # sharded commits the params to the mesh ONCE here (the
        # residents every phase program shares as runtime args) and
        # every (phase, rows, seq) rung compiles as a pjit program
        # with weight in_shardings + replicated batch/kv/outputs. The
        # descriptor rides in every ArtifactKey: the sharded decode
        # ladder is its own store identity.
        self._mesh = _sharding.resolve(mesh)
        self.mesh_desc = self._mesh.descriptor
        self._sharded_params = None
        if not self._mesh.is_single:
            self._mesh.build()  # fail fast with the device-count remedy
            self._sharded_params = self._mesh.shard_arrays(
                [jax.numpy.asarray(p) for p in model.params])

    # ----------------------------------------------------------- identity
    def _fingerprint(self):
        """Model identity for store keys and KV-snapshot headers,
        computed once: sha256 of the step program's *location-free*
        module text at the canonical (2, 8) shape (the raw serialized
        export embeds MLIR debug locations that vary with in-process
        trace order — see ``canonical_module_bytes``; a snapshot resume
        between replicas must compare program identity, not tracing
        provenance). Returns None when the model cannot export (store
        is then skipped — inline compiles, the store-less behaviour)."""
        m = self._model
        if m._fingerprint is None:
            with self._fp_lock:
                if m._fingerprint is None:
                    try:
                        blob = canonical_module_bytes(
                            self._export("step", 2, 8))
                        m._fingerprint = model_fingerprint(
                            blob, quant=getattr(m, "quant", None))
                    except Exception:  # noqa: BLE001 - store-less fallback
                        m._fingerprint = False
        return m._fingerprint or None

    def _weights_digest(self):
        """Parameter-VALUE identity for KV-snapshot headers: sha256
        over every param's dtype/shape/bytes. The program fingerprint
        deliberately excludes weight values (they are runtime args, so
        compiled artifacts are reusable across fine-tunes) — but a KV
        cache is a function of the weights, so resume must compare
        them. Computed once per model; weights are immutable in a
        serving replica."""
        if self._weights_digest_cached is None:
            with self._fp_lock:
                if self._weights_digest_cached is None:
                    h = hashlib.sha256()
                    for p in self._model.params:
                        a = np.ascontiguousarray(np.asarray(p))
                        h.update(str(a.dtype).encode())
                        h.update(str(a.shape).encode())
                        h.update(a.tobytes())
                    self._weights_digest_cached = h.hexdigest()
        return self._weights_digest_cached

    def _active_store(self):
        if self._store is None or _artifacts.disabled():
            return None
        if self._fingerprint() is None:
            return None
        return self._store

    def _quant_extra(self):
        """Ledger-event mode/mesh tags (empty for f32/single —
        historical event shapes and the committed perfproxy decode
        section stay byte-identical)."""
        extra = {}
        q = getattr(self._model, "quant", None)
        if q:
            extra["quant"] = q
        if self.mesh_desc != _sharding.SINGLE:
            extra["mesh"] = self.mesh_desc
        return extra

    def _artifact_key(self, phase, rows, seq):
        # the phase + seq bucket ride in the signature (the ArtifactKey
        # schema has one integer bucket): a synthetic leading entry
        # ("decode:<phase>", (seq,)) keys them unambiguously alongside
        # the kv/feature avals
        m = self._model
        if phase == "verify":
            # k is part of the program's identity: a k=3 verify ladder
            # never collides with a k=4 one in the store
            sig = (("decode:verify", (int(seq), self._spec_k)),)
        else:
            sig = ((f"decode:{phase}", (int(seq),)),)
        sig += tuple((str(dt), tr) for tr, dt in m.kv_spec)
        sig += tuple((str(dt), tr) for tr, dt in m.feature_spec)
        sig += ((f"vocab{m.vocab_size}", ()),)
        return _artifacts.ArtifactKey(self._fingerprint(), int(rows), sig,
                                      mesh=self.mesh_desc,
                                      quant=getattr(m, "quant", None))

    # ------------------------------------------------------------- shapes
    def _in_specs(self, phase, rows, seq):
        """ShapeDtypeStructs for one program's inputs (past params)."""
        jax = self._jax
        m = self._model
        i32 = np.dtype(np.int32)
        if phase == "prefill":
            specs = [jax.ShapeDtypeStruct((rows, seq), i32),   # tokens
                     jax.ShapeDtypeStruct((rows,), i32)]       # lengths
        elif phase == "verify":
            specs = [jax.ShapeDtypeStruct((rows, self._spec_k), i32),
                     jax.ShapeDtypeStruct((rows,), i32)]       # start pos
            specs += [jax.ShapeDtypeStruct((rows, seq) + tr, dt)
                      for tr, dt in m.kv_spec]
        else:
            specs = [jax.ShapeDtypeStruct((rows,), i32),       # tokens
                     jax.ShapeDtypeStruct((rows,), i32)]       # positions
            specs += [jax.ShapeDtypeStruct((rows, seq) + tr, dt)
                      for tr, dt in m.kv_spec]
        specs += [jax.ShapeDtypeStruct((rows,) + tr, dt)
                  for tr, dt in m.feature_spec]
        return specs

    def _flat_fn(self, phase):
        m = self._model
        if phase == "verify":
            return self._verify_fn()

        def flat(param_list, *args):
            fn = m.prefill_fn if phase == "prefill" else m.step_fn
            out = fn(param_list, *args)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        return flat

    def _verify_fn(self):
        """The batched speculative-verify program, auto-derived from
        the model's step_fn: k unrolled step-shaped iterations in ONE
        jit. Iteration i feeds ``tokens[:, i]`` at ``positions + i``
        against the KV written so far (the incoming gathered buffers
        plus the i entries the earlier iterations produced — committed
        in-program with the same ``.at[...].set`` write the host
        performs between sequential dispatches). Returns the per-
        position logits ``[rows, k, vocab]`` and the fresh KV entries
        ``[rows, k, *tr]``; the HOST commits only the accepted prefix
        of those entries (rejected-run rollback = don't write)."""
        m = self._model
        K = self._spec_k
        nkv = len(m.kv_spec)
        jnp = self._jax.numpy

        def flat(param_list, tokens, positions, *rest):
            kv = list(rest[:nkv])
            feats = rest[nkv:]
            rows = jnp.arange(tokens.shape[0])
            logits, entries = [], [[] for _ in range(nkv)]
            for i in range(K):
                out = m.step_fn(param_list, tokens[:, i], positions + i,
                                *kv, *feats)
                logits.append(out[0])
                for j in range(nkv):
                    entries[j].append(out[1 + j])
                    kv[j] = kv[j].at[rows, positions + i].set(out[1 + j])
            return ((jnp.stack(logits, axis=1),)
                    + tuple(jnp.stack(e, axis=1) for e in entries))

        return flat

    def _state(self, phase, rows, seq):
        jax = self._jax
        if self._sharded_params is not None:
            param_arrays, p_sh = self._sharded_params
            repl = self._mesh.replicated()
            param_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                sharding=s)
                           for a, s in zip(param_arrays, p_sh)]
            in_specs = [jax.ShapeDtypeStruct(s.shape, s.dtype,
                                             sharding=repl)
                        for s in self._in_specs(phase, rows, seq)]
        else:
            param_arrays = [jax.numpy.asarray(p)
                            for p in self._model.params]
            param_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                           for a in param_arrays]
            in_specs = self._in_specs(phase, rows, seq)
        donate = ()
        if phase in ("step", "verify"):
            # donate the gathered kv scratch buffers (args: params,
            # tokens, positions, kv..., feat...): they are rebuilt
            # host-side every step, so the program may overwrite them
            nkv = len(self._model.kv_spec)
            donate = tuple(range(3, 3 + nkv))
        return param_arrays, param_specs, in_specs, donate

    def _jit(self, phase, donate, n_inputs):
        """One jit construction for both the inline compile and the
        export. Single mesh: the historical call, byte-for-byte.
        Sharded: params in their discipline layout, every batch/kv
        input and every output replicated — the host engine's shapes
        (and the wire) are mesh-invariant."""
        jax = self._jax
        if self._sharded_params is None:
            return jax.jit(self._flat_fn(phase), donate_argnums=donate)
        _, p_sh = self._sharded_params
        repl = self._mesh.replicated()
        return jax.jit(self._flat_fn(phase), donate_argnums=donate,
                       in_shardings=(list(p_sh), *([repl] * n_inputs)),
                       out_shardings=repl)

    # ------------------------------------------------------------ compile
    def _export(self, phase, rows, seq, state=None):
        from jax import export as jax_export

        _, param_specs, in_specs, donate = \
            state if state is not None else self._state(phase, rows, seq)
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return jax_export.export(
                self._jit(phase, donate, len(in_specs)))(
                    param_specs, *in_specs)

    def _probe_batch(self, phase, rows, seq):
        m = self._model
        i32 = np.int32
        if phase == "prefill":
            batch = [np.zeros((rows, seq), i32), np.ones((rows,), i32)]
        elif phase == "verify":
            batch = [np.zeros((rows, self._spec_k), i32),
                     np.zeros((rows,), i32)]
            batch += [np.zeros((rows, seq) + tr, dt)
                      for tr, dt in m.kv_spec]
        else:
            batch = [np.zeros((rows,), i32), np.zeros((rows,), i32)]
            batch += [np.zeros((rows, seq) + tr, dt)
                      for tr, dt in m.kv_spec]
        batch += [np.zeros((rows,) + tr, dt) for tr, dt in m.feature_spec]
        return batch

    def _check_outputs(self, outs, phase, rows):
        m = self._model
        want = 1 + len(m.kv_spec)
        if len(outs) != want:
            raise ValueError(
                f"{phase} program returned {len(outs)} outputs, "
                f"expected logits + {len(m.kv_spec)} kv arrays")
        lg = outs[0]
        want_lg = ((rows, self._spec_k, m.vocab_size)
                   if phase == "verify" else (rows, m.vocab_size))
        if tuple(getattr(lg, "shape", ())) != want_lg:
            raise ValueError(
                f"{phase} logits shape {getattr(lg, 'shape', ())} != "
                f"{want_lg}")
        for o in outs[1:]:
            if getattr(o, "ndim", 0) == 0 or o.shape[0] != rows:
                raise ValueError(
                    f"{phase} kv output shape {getattr(o, 'shape', ())} "
                    f"does not keep the {rows}-row batch dim")

    def _make_run(self, exported, phase, rows, seq, state=None):
        """Run callable over an exported module, gated by everything
        bytes alone cannot prove (aval match, zero-batch probe) —
        mirrors AotLayerRunner._make_run."""
        param_arrays, param_specs, in_specs, _ = \
            state if state is not None else self._state(phase, rows, seq)
        # defense in depth against a copied store dir / hand-loaded
        # blob: key.mesh already makes skew a clean miss
        _sharding.check_nr_devices(
            exported, None if self._sharded_params is None else self._mesh)
        canon = self._jax.dtypes.canonicalize_dtype
        expect = [(tuple(s.shape), np.dtype(canon(s.dtype)))
                  for s in (*param_specs, *in_specs)]
        got = [(tuple(a.shape), np.dtype(a.dtype))
               for a in exported.in_avals]
        if got != expect:
            raise ValueError(
                f"aval mismatch: artifact {got} vs expected {expect}")

        def run(batch):
            out = exported.call(param_arrays, *batch)
            return [np.asarray(o) for o in out]

        outs = run(self._probe_batch(phase, rows, seq))
        self._check_outputs(outs, phase, rows)
        return run

    def _compile_inline(self, phase, rows, seq):
        param_arrays, param_specs, in_specs, donate = \
            self._state(phase, rows, seq)
        t0 = time.monotonic()
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = (self._jit(phase, donate, len(in_specs))
                        .lower(param_specs, *in_specs).compile())
        LEDGER.record(f"decode/{phase}{rows}x{seq}",
                      duration_s=time.monotonic() - t0, compiled=compiled,
                      kind="aot",
                      extra={"phase": phase, "bucket": rows, "seq": seq,
                             **self._quant_extra()})

        def run(batch):
            out = compiled(param_arrays, *batch)
            return [np.asarray(o) for o in out]

        outs = run(self._probe_batch(phase, rows, seq))
        self._check_outputs(outs, phase, rows)
        return run

    def compile(self, phase, rows, seq, warming=False):
        """-> (run, source) for one ladder rung, through the shared
        store-backed flow (store load / export+publish / inline)."""
        store = self._active_store()
        if store is None:
            return self._compile_inline(phase, rows, seq), "inline"
        key = self._artifact_key(phase, rows, seq)

        def export_and_run():
            t0 = time.monotonic()
            state = self._state(phase, rows, seq)
            exported = self._export(phase, rows, seq, state=state)
            blob = serialize_exported(exported)
            run = self._make_run(exported, phase, rows, seq, state=state)
            LEDGER.record(f"decode/{phase}{rows}x{seq}",
                          duration_s=time.monotonic() - t0, kind="aot",
                          extra={"phase": phase, "bucket": rows,
                                 "seq": seq, "via": "export",
                                 **self._quant_extra()})
            return blob, run

        def run_from_payload(payload):
            t0 = time.monotonic()
            try:
                exported = deserialize_exported(payload)
                run = self._make_run(exported, phase, rows, seq)
            except Exception as e:  # noqa: BLE001 - bad artifact degrades
                store.quarantine(key, str(e))
                return None
            LEDGER.record(f"decode/{phase}{rows}x{seq}",
                          duration_s=time.monotonic() - t0, kind="store",
                          extra={"phase": phase, "bucket": rows,
                                 "seq": seq, "artifact": key.digest(),
                                 **self._quant_extra()})
            return run

        return store_backed_compile(
            store, key,
            inline_fn=lambda: self._compile_inline(phase, rows, seq),
            export_and_run=export_and_run,
            run_from_payload=run_from_payload,
            warming=warming, warmup_wait_s=self._warmup_wait_s)

    def store_stats(self):
        store = self._active_store()
        return store.stats() if store is not None else None


class _KVSlots:
    """Paged per-sequence KV storage over a REFCOUNTED page pool.

    Each slot's KV is a list of fixed-size pages (``page_len`` =
    ``min_bucket`` tokens) drawn from a shared pool, so host memory
    tracks actual sequence lengths AND hot prefixes can live once:
    the prefix cache installs its pages into a fresh slot by reference
    (:meth:`install_shared`). Sharing is copy-on-write — any write
    into a page with refcount > 1 clones it first, so two sequences
    sharing a prefix then diverging can never see each other's pages.
    Release DECREMENTS, never frees: a page the cache or another
    sequence still holds survives a slot's release (the shared-page
    half of the exactly-once release discipline — a watchdog restart's
    sweep decrefs shared pages, it cannot double-free them). ``gather``
    assembles the fixed-shape step batch, zero-filling rows beyond
    each sequence's length so stale contents never reach a program."""

    def __init__(self, max_slots, max_seq_len, kv_spec, min_bucket=8):
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len)
        self.kv_spec = kv_spec
        self.min_bucket = int(min_bucket)
        self.page_len = self.min_bucket
        self._free = list(range(self.max_slots - 1, -1, -1))
        self._slot_pages = [[] for _ in range(self.max_slots)]
        self._pages = {}       # page id -> [np (page_len, *tr) per kv]
        self._rc = {}          # page id -> refcount
        self._spare = []       # recycled page array lists (no realloc
        self._next_pid = 0     # churn at steady state)

    def free_count(self):
        return len(self._free)

    def page_bytes(self):
        """Host bytes of ONE page across every kv buffer (what the
        prefix cache budgets with)."""
        return sum(self.page_len * int(np.prod(tr)) * dt.itemsize
                   for tr, dt in self.kv_spec)

    # ------------------------------------------------------------ pages
    # tpu-resource: acquires=kv_page
    def _page_alloc(self):
        """One fresh page (refcount 1) — recycled arrays when possible.
        Recycled contents are NOT zeroed: every read path copies only
        positions a sequence actually wrote (gather/snapshot bound by
        length), so stale bytes can never reach a program."""
        pid = self._next_pid
        self._next_pid += 1
        if self._spare:
            self._pages[pid] = self._spare.pop()
        else:
            self._pages[pid] = [np.zeros((self.page_len,) + tr, dt)
                                for tr, dt in self.kv_spec]
        self._rc[pid] = 1
        return pid

    # tpu-resource: releases=kv_page
    def _page_reclaim(self, pid):
        """Refcount hit zero: return the arrays to the spare pool."""
        self._spare.append(self._pages.pop(pid))
        del self._rc[pid]

    def retain_page(self, pid):
        self._rc[pid] += 1

    def drop_page(self, pid):
        rc = self._rc[pid] - 1
        if rc:
            self._rc[pid] = rc
        else:
            self._page_reclaim(pid)

    def shared_pages(self):
        """Pages held by more than one owner (slots + cache entries)."""
        return sum(1 for rc in self._rc.values() if rc > 1)

    def live_pages(self):
        return len(self._pages)

    def _ensure(self, slot, n):
        """Grow the slot's page list to cover n positions."""
        if n > self.max_seq_len:
            raise ValueError(f"sequence length {n} exceeds max_seq_len "
                             f"{self.max_seq_len}")
        pages = self._slot_pages[slot]
        need = -(-n // self.page_len)
        while len(pages) < need:
            pages.append(self._page_alloc())

    def _writable(self, slot, page_idx):
        """The slot's page arrays at ``page_idx``, cloned first if the
        page is shared — the copy-on-write barrier every write path
        goes through."""
        pages = self._slot_pages[slot]
        pid = pages[page_idx]
        if self._rc[pid] > 1:
            new = self._page_alloc()
            for dst, src in zip(self._pages[new], self._pages[pid]):
                dst[:] = src
            self.drop_page(pid)
            pages[page_idx] = new
            pid = new
        return self._pages[pid]

    # ------------------------------------------------------------ slots
    # tpu-resource: acquires=kv_slot
    def alloc(self):
        return self._free.pop() if self._free else None

    # tpu-resource: releases=kv_slot
    def release(self, slot):
        """Free the slot: DECREMENT every page (reclaimed only when no
        other sequence or cache entry holds it)."""
        pages = self._slot_pages[slot]
        self._slot_pages[slot] = []
        for pid in pages:
            self.drop_page(pid)
        self._free.append(slot)

    def install_shared(self, slot, pages):
        """Seed a freshly-allocated slot with cached prefix pages by
        reference (each page's refcount grows; the first divergent
        write clones via :meth:`_writable`)."""
        for pid in pages:
            self.retain_page(pid)
        self._slot_pages[slot] = list(pages)

    def export_pages(self, slot, n_pages):
        """The slot's first ``n_pages`` page ids (for the prefix cache
        to retain — the pages themselves stay put)."""
        return list(self._slot_pages[slot][:n_pages])

    def pages_from_arrays(self, kv_arrays, length):
        """Materialize contiguous KV arrays (a store-loaded prefix)
        into fresh pool pages; returns the page ids, refcount 1 each,
        owned by the caller."""
        pages = []
        pl = self.page_len
        for pi in range(-(-length // pl)):
            pid = self._page_alloc()
            lo = pi * pl
            m = min(pl, length - lo)
            for a, src in zip(self._pages[pid], kv_arrays):
                a[:m] = src[lo:lo + m]
            pages.append(pid)
        return pages

    # ----------------------------------------------------------- writes
    def write_prefill(self, slot, kv_arrays, length):
        """Install a fresh sequence's prompt kv (row slices of the
        prefill program's [rows, prompt_bucket, ...] outputs)."""
        length = max(length, 1)
        self._ensure(slot, length)
        pl = self.page_len
        for pi in range(-(-length // pl)):
            lo = pi * pl
            m = min(pl, length - lo)
            arrays = self._writable(slot, pi)
            for a, src in zip(arrays, kv_arrays):
                a[:m] = src[lo:lo + m]

    def write_entry(self, slot, pos, entries):
        """Append one decode step's kv entries at position ``pos``."""
        self._ensure(slot, pos + 1)
        arrays = self._writable(slot, pos // self.page_len)
        o = pos % self.page_len
        for a, e in zip(arrays, entries):
            a[o] = e

    # ------------------------------------------------------------ reads
    def snapshot(self, slot, length):
        """Copy slot ``slot``'s first ``length`` KV entries out (one
        contiguous array per kv_spec entry) — the paged-KV payload of
        a resumable stream snapshot. Pure read: the slot stays live."""
        out = [np.zeros((length,) + tr, dt) for tr, dt in self.kv_spec]
        pl = self.page_len
        for pi, pid in enumerate(self._slot_pages[slot]):
            lo = pi * pl
            if lo >= length:
                break
            m = min(pl, length - lo)
            for o, a in zip(out, self._pages[pid]):
                o[lo:lo + m] = a[:m]
        return out

    # tpu-resource: acquires=kv_slot
    def restore(self, kv_arrays, length):
        """Allocate a slot and install a snapshot's KV prefix into it
        (the write_prefill of a resumed sequence). Returns the slot,
        or None when no slot is free."""
        slot = self.alloc()
        if slot is None:
            return None
        self.write_prefill(slot, kv_arrays, length)
        return slot

    def gather(self, slots, lengths, rows_bucket, seq_b):
        """[rows_bucket, seq_b, *tr] per kv buffer: row i carries slot
        ``slots[i]``'s first ``lengths[i]`` entries, zeros elsewhere
        (zero pad rows AND zero beyond-length tails — finite by
        construction, masked out by the model)."""
        out = [np.zeros((rows_bucket, seq_b) + tr, dt)
               for tr, dt in self.kv_spec]
        pl = self.page_len
        for i, (slot, n) in enumerate(zip(slots, lengths)):
            n = min(n, seq_b)
            if n <= 0:
                continue
            for pi, pid in enumerate(self._slot_pages[slot]):
                lo = pi * pl
                if lo >= n:
                    break
                m = min(pl, n - lo)
                for o, a in zip(out, self._pages[pid]):
                    o[i, lo:lo + m] = a[:m]
        return out


_RETIRE_REASONS = ("eos", "max_tokens", "max_seq_len", "deadline",
                   "error", "cancelled")


class DecodeRequest:
    """One streaming decode request: thread-safe token sink the engine
    pushes into and a consumer (the server handler, or a direct
    :meth:`result` caller) drains.

    Consumer API:
      - ``next_tokens(timeout)`` -> ``(tokens, done)``: blocks for new
        tokens; delivers whatever accumulated since the last call.
        Once the terminal error (if any) is the only thing left, it
        raises it — delivered tokens always come out first, so a
        streaming client sees the real prefix then the retryable
        error, never a truncated-but-ok sequence.
      - ``result(timeout)`` -> full token array (raises on error).
      - ``cancel()``: abandon; the engine purges the KV slot at the
        next iteration boundary and stops spending compute.
    """

    __slots__ = ("prompt", "features", "max_new_tokens", "eos_token_id",
                 "token_budget_s", "trace_id", "token_dtype", "t_enqueue",
                 "snapshot_every", "speculative", "_cond", "_tokens",
                 "_taken", "_done", "_error", "_snap", "_snap_fresh",
                 "finish_reason", "cancelled")

    def __init__(self, prompt, features, max_new_tokens, eos_token_id,
                 token_budget_s, trace_id, token_dtype):
        self.prompt = prompt
        self.features = features
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.token_budget_s = token_budget_s
        self.trace_id = trace_id
        self.token_dtype = token_dtype
        self.t_enqueue = time.monotonic()
        self.snapshot_every = 0
        self.speculative = False
        self._cond = threading.Condition()
        self._tokens = []
        self._taken = 0
        self._done = False
        self._error = None
        self._snap = None
        self._snap_fresh = False
        self.finish_reason = None
        self.cancelled = False

    # ------------------------------------------------------- engine side
    def _push(self, token):
        with self._cond:
            if self._done:
                return  # a superseded scheduler's late result: discard
            self._tokens.append(token)
            self._cond.notify_all()

    def _finish(self, reason):
        with self._cond:
            if not self._done:
                self._done = True
                self.finish_reason = reason
                self._cond.notify_all()

    def _fail(self, error):
        with self._cond:
            if not self._done:
                self._done = True
                self._error = error
                self.finish_reason = "error"
                self._cond.notify_all()

    def _push_snapshot(self, blob, n_generated):
        """Install the latest kv-snapshot block for this sequence
        (engine side, at the request's cadence). Only the newest
        snapshot is kept — a resume always restarts from the most
        recent position. ``n_generated`` rides along so the server can
        hold a snapshot frame until every token it covers is on the
        wire (the router's dedup arithmetic needs delivered >= G)."""
        with self._cond:
            if self._done:
                return
            self._snap = (blob, int(n_generated))
            self._snap_fresh = True
            self._cond.notify_all()

    # ----------------------------------------------------- consumer side
    def cancel(self):
        """Abandon the request: tokens stop, the engine frees the KV
        slot at its next iteration boundary (or drops the request from
        the queue if it never joined)."""
        with self._cond:
            self.cancelled = True
            if not self._done:
                self._done = True
                self.finish_reason = "cancelled"
                self._cond.notify_all()

    def next_tokens(self, timeout=None):
        """-> (new_tokens_list, done). Raises the terminal error once
        every delivered token has been consumed; raises TimeoutError
        if nothing happens within ``timeout``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._taken < len(self._tokens):
                    out = self._tokens[self._taken:]
                    self._taken = len(self._tokens)
                    return out, self._done and self._error is None
                if self._done:
                    if self._error is not None:
                        raise self._error
                    return [], True
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError(
                        "no decode progress within timeout")
                self._cond.wait(left)  # tpu-lint: disable=TPU303  # bounded by caller timeout; None is the documented no-timeout mode

    def result(self, timeout=None):
        """Block until the sequence finishes; -> 1-D token array in the
        request's token dtype."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._done:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError("decode did not finish in time")
                self._cond.wait(left)  # tpu-lint: disable=TPU303  # bounded by caller timeout; None is the documented no-timeout mode
            if self._error is not None:
                raise self._error
            return np.asarray(self._tokens, dtype=self.token_dtype)

    def tokens_so_far(self):
        with self._cond:
            return list(self._tokens)

    def take_snapshot(self):
        """-> ``(block, n_generated)`` for the newest kv-snapshot not
        yet taken, or None. Take-once semantics: the server handler
        calls this after each token drain and forwards the block as a
        snapshot frame once ``n_generated`` tokens have been sent."""
        with self._cond:
            if not self._snap_fresh:
                return None
            self._snap_fresh = False
            return self._snap

    def latest_snapshot(self):
        """-> the newest kv-snapshot block (without consuming it), or
        None if the sequence never reached its cadence."""
        with self._cond:
            return None if self._snap is None else self._snap[0]


class _Seq:
    """One RUNNING sequence: its request, KV slot, and positions.
    ``draft_slot``/``draft_pos`` track the speculative companion's KV
    (allocated lazily on the first speculative iteration; rollback
    after a rejected run is just moving ``draft_pos`` back — the stale
    entries beyond it are never gathered)."""

    __slots__ = ("req", "slot", "pos", "last_token", "n_generated",
                 "t_last", "draft_slot", "draft_pos")

    def __init__(self, req, slot, pos, last_token, now):
        self.req = req
        self.slot = slot
        self.pos = pos  # kv entries cached so far
        self.last_token = last_token
        self.n_generated = 1  # prefill emitted the first token
        self.t_last = now
        self.draft_slot = None
        self.draft_pos = 0


class DecodeEngine:
    """Continuous-batching decode front end (see module docstring).

    ``submit`` enqueues a sequence and returns its
    :class:`DecodeRequest` (stream with ``next_tokens`` or block with
    ``result``); ``generate`` is the blocking convenience. Any number
    of threads may submit concurrently; one scheduler thread runs the
    iteration loop."""

    def __init__(self, model, max_slots=None, max_seq_len=None,
                 max_queue=None, min_seq_bucket=None, max_prompt_len=None,
                 default_max_new_tokens=None, name="decode", store=None,
                 breaker_threshold=None, breaker_cooldown=None,
                 watchdog_interval=None, wedge_timeout=None, quant=None,
                 mesh=None, phase=None, spec_k=None, prefix=None,
                 prefix_dir=None, prefix_max_bytes=None):
        # quant: serve this model under a quantization mode ("w8" |
        # "bf16w"; env default PADDLE_TPU_SERVING_QUANT — the one-knob
        # fleet flip). An unquantized model is wrapped via
        # quantization.quantize_decode_model; a model ALREADY carrying
        # a mode must match the request (a replica told to serve w8
        # must never silently serve something else).
        # mesh: serving mesh descriptor ("tp2" | "fsdp2xtp2" | ...; env
        # default PADDLE_TPU_SERVING_MESH) — params shard once at
        # construction and the whole (phase, rows, seq) program ladder
        # becomes per-(bucket, mesh) pjit programs with their own
        # artifact-store identities (README "Sharded serving").
        if quant is None:
            quant = os.environ.get("PADDLE_TPU_SERVING_QUANT") or None
        # capture the draft companion BEFORE any quant wrapping:
        # quantize_decode_model builds a NEW DecodeModel and would drop
        # the attribute. The draft follows the target's serving mode
        # unless it already carries its own (a pre-quantized draft —
        # the bf16w/w8 draft of the ISSUE contract — wins).
        draft_model = getattr(model, "draft", None)
        model_quant = getattr(model, "quant", None)
        if quant is not None and quant != (model_quant or "f32"):
            if model_quant is not None:
                raise ValueError(
                    f"model is quantized as {model_quant!r} but the "
                    f"engine was asked to serve {quant!r}")
            if quant != "f32":
                from ..quantization.serving import quantize_decode_model

                model = quantize_decode_model(model, quant)
                if (draft_model is not None
                        and getattr(draft_model, "quant", None) is None):
                    draft_model = quantize_decode_model(draft_model, quant)
        if draft_model is not None:
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.vocab_size} != target "
                    f"vocab {model.vocab_size}; speculative verify "
                    "compares argmaxes over the SAME vocab")
            if draft_model.feature_spec != model.feature_spec:
                raise ValueError(
                    "draft feature_spec differs from the target's; "
                    "both models consume the request's feature arrays")
        self._model = model
        self.max_slots = int(
            max_slots if max_slots is not None
            else _env_int("PADDLE_TPU_DECODE_MAX_SLOTS", 8))
        self.max_seq_len = int(
            max_seq_len if max_seq_len is not None
            else _env_int("PADDLE_TPU_DECODE_MAX_SEQ_LEN", 256))
        self.max_queue = int(
            max_queue if max_queue is not None
            else _env_int("PADDLE_TPU_DECODE_MAX_QUEUE", 64))
        self.min_seq_bucket = int(
            min_seq_bucket if min_seq_bucket is not None
            else _env_int("PADDLE_TPU_DECODE_MIN_SEQ_BUCKET", 8))
        self.max_prompt_len = int(
            max_prompt_len if max_prompt_len is not None
            else _env_int("PADDLE_TPU_DECODE_MAX_PROMPT_LEN",
                          self.max_seq_len))
        self.default_max_new_tokens = int(
            default_max_new_tokens if default_max_new_tokens is not None
            else _env_int("PADDLE_TPU_DECODE_MAX_NEW_TOKENS", 64))
        self.default_snapshot_every = max(0, _env_int(
            "PADDLE_TPU_DECODE_SNAPSHOT_EVERY", 0))
        # phase: this engine's pool in a disaggregated fleet ("prefill"
        # | "decode" | "both"; env default PADDLE_TPU_DECODE_PHASE).
        # Phase is a PLACEMENT attribute — it shapes the warmup ladder
        # and is reported in health/stats for the router, but the
        # engine still serves every request kind so a fleet whose other
        # pool collapsed can degrade to colocated serving here.
        if phase is None:
            phase = os.environ.get("PADDLE_TPU_DECODE_PHASE") or "both"
        if phase not in _wire_spec.REPLICA_PHASES:
            raise ValueError(
                f"unknown engine phase {phase!r} (expected one of "
                f"{_wire_spec.REPLICA_PHASES})")
        self.phase = phase
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        # row buckets are floored at 2 even for a max_slots=1 engine
        # (one pad row): batch-1 float matmuls hit XLA's gemv regime,
        # whose rounding differs from the gemm every batch >= 2 uses —
        # keeping EVERY dispatch in the gemm regime is what makes a
        # solo decode bitwise comparable to the same sequence inside a
        # continuous batch (the PR 4 lesson, applied per decode step)
        self._rows_cap = max(2, self.max_slots)
        if self.max_prompt_len > self.max_seq_len:
            raise ValueError("max_prompt_len cannot exceed max_seq_len")
        self.breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else _env_int("PADDLE_TPU_SERVING_BREAKER_THRESHOLD", 3))
        self.breaker_cooldown = float(
            breaker_cooldown if breaker_cooldown is not None
            else _env_float("PADDLE_TPU_SERVING_BREAKER_COOLDOWN", 5.0))
        self.watchdog_interval = float(
            watchdog_interval if watchdog_interval is not None
            else _env_float("PADDLE_TPU_SERVING_WATCHDOG_INTERVAL", 0.5))
        self.wedge_timeout = float(
            wedge_timeout if wedge_timeout is not None
            else _env_float("PADDLE_TPU_SERVING_WEDGE_TIMEOUT", 30.0))
        self.name = name
        # speculative decode: active only with a draft companion AND
        # k >= 2 (k-1 proposed tokens + the always-correct first
        # position per verify dispatch)
        self._spec_k = int(spec_k if spec_k is not None
                           else _env_int("PADDLE_TPU_SPEC_K", 0))
        if draft_model is None or self._spec_k < 2:
            self._spec_k = 0
        self.spec_enabled = self._spec_k >= 2
        self._programs = _Programs(model, store=store, mesh=mesh,
                                   spec_k=self._spec_k)
        self.mesh_desc = self._programs.mesh_desc
        self._draft_programs = None
        self._draft_slots = None
        if self.spec_enabled:
            self._draft_programs = _Programs(draft_model, store=store,
                                             mesh=mesh)
            self._draft_slots = _KVSlots(
                self.max_slots, self.max_seq_len, draft_model.kv_spec,
                min_bucket=self.min_seq_bucket)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = []  # FIFO of DecodeRequest
        self._pending_resume = []  # FIFO of (req, kv_arrays, state dict)
        self._n_snapshots = 0       # blocks assembled (stats view)
        self._n_resumes_ok = 0      # resume joins admitted
        self._n_resumes_refused = 0  # identity-skew refusals
        self._n_spec_iters = 0      # speculative bursts applied
        self._n_spec_accepted = 0   # draft tokens accepted by verify
        self._active = []   # list of _Seq (scheduler-owned mutation)
        self._inflight_join = []  # joiners popped but not yet prefilled:
        # a scheduler that dies holding them must not strand them — the
        # watchdog restart fails exactly these (retryable), like the
        # one-shot engine's _inflight group
        self._slots = _KVSlots(self.max_slots, self.max_seq_len,
                               model.kv_spec,
                               min_bucket=self.min_seq_bucket)
        # content-addressed prefix cache over the slot page pool (ON
        # by default — in-memory sharing alone; the persistent tier
        # needs PADDLE_TPU_PREFIX_DIR)
        if prefix is None:
            prefix = os.environ.get("PADDLE_TPU_PREFIX_DISABLE") != "1"
        self._prefix = None
        if prefix:
            self._prefix = PrefixCache(
                self._slots, identity_fn=self._prefix_identity,
                max_bytes=prefix_max_bytes, store_dir=prefix_dir,
                name=f"{name}-prefix")
        self._cache = {}      # (phase, rows, seq) -> run
        self._compiling = {}  # (phase, rows, seq) -> Event
        self._breakers = {}   # (phase, rows, seq) -> _Breaker
        self._compile_counts = {}  # (phase, rows, seq) -> {source: n}
        self._declared = []
        self._closed = False
        self._closed_ev = threading.Event()
        self._sched_gen = 0
        self._heartbeat = time.monotonic()
        self._init_metrics()
        self._watchdog = None
        self._scheduler = threading.Thread(
            target=self._run_scheduler, args=(0,),
            name=f"{name}-scheduler", daemon=True)
        self._scheduler.start()
        if self.watchdog_interval > 0:
            self._watchdog = threading.Thread(
                target=self._run_watchdog, name=f"{name}-watchdog",
                daemon=True)
            self._watchdog.start()

    # -------------------------------------------------------- telemetry
    def _init_metrics(self):
        cl = {"engine": self.name}
        M = obs_metrics
        lat = M.log_buckets(0.0001, 4.0, 10)
        self._m_requests = M.Counter(
            "paddle_decode_requests_total",
            "Decode requests admitted", const_labels=cl)
        self._m_tokens = M.Counter(
            "paddle_decode_tokens_total",
            "Tokens generated", const_labels=cl)
        self._m_shed = M.Counter(
            "paddle_decode_shed_total",
            "Requests shed (reason: queue_full | quarantine | "
            "no_free_slot — the last is the kv_put seed preflight)",
            labelnames=("reason",), const_labels=cl)
        self._m_retired = M.Counter(
            "paddle_decode_retired_total",
            "Sequences retired, by reason",
            labelnames=("reason",), const_labels=cl)
        self._m_deadline = M.Counter(
            "paddle_decode_deadline_total",
            "Per-token deadline outcomes (stage: expired = purged "
            "before joining, zero compute; late = blew a per-token "
            "budget mid-sequence)",
            labelnames=("stage",), const_labels=cl)
        self._m_restarts = M.Counter(
            "paddle_decode_scheduler_restarts_total",
            "Watchdog scheduler restarts", const_labels=cl)
        self._m_compiles = M.Counter(
            "paddle_decode_compiles_total",
            "Program materializations (source: inline = real XLA "
            "compile, store = artifact-store load; quant: the serving "
            "quantization mode; mesh: the serving mesh descriptor)",
            labelnames=("phase", "source"),
            const_labels={
                **cl,
                "quant": getattr(self._model, "quant", None) or "f32",
                "mesh": self.mesh_desc})
        self._m_steps = M.Counter(
            "paddle_decode_steps_total",
            "Model program dispatches, by phase",
            labelnames=("phase",), const_labels=cl)
        self._m_ttft = M.Histogram(
            "paddle_decode_ttft_seconds",
            "Time from enqueue to a sequence's FIRST token",
            const_labels=cl, buckets=lat)
        self._m_intertoken = M.Histogram(
            "paddle_decode_intertoken_seconds",
            "Gap between consecutive tokens of one sequence",
            const_labels=cl, buckets=lat)
        self._m_step_exec = M.Histogram(
            "paddle_decode_step_seconds",
            "Program execute duration, by phase",
            labelnames=("phase",), const_labels=cl, buckets=lat)
        self._m_occupancy = M.Histogram(
            "paddle_decode_batch_occupancy",
            "Active sequences / slot bucket per decode step",
            const_labels=cl,
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self._m_active = M.Gauge(
            "paddle_decode_active_slots",
            "Sequences currently holding a KV slot", const_labels=cl)
        self._m_queue = M.Gauge(
            "paddle_decode_queue_depth",
            "Requests waiting for a slot", const_labels=cl)
        self._m_prefix_hits = M.Counter(
            "paddle_prefix_hits_total",
            "Prefix-cache hits (a joiner installed cached KV pages and "
            "skipped prefill over them)", const_labels=cl)
        self._m_prefix_misses = M.Counter(
            "paddle_prefix_misses_total",
            "Prefix-cache misses (hashed prompts with no cached "
            "boundary)", const_labels=cl)
        self._m_prefix_evictions = M.Counter(
            "paddle_prefix_evictions_total",
            "Prefix-cache entries evicted under the byte budget",
            const_labels=cl)
        self._m_shared_pages = M.Gauge(
            "paddle_decode_shared_pages",
            "KV pages referenced by more than one owner (slots + "
            "prefix-cache entries)", const_labels=cl)
        self._m_live_pages = M.Gauge(
            "paddle_decode_live_pages",
            "KV pages currently allocated (target + draft pools)",
            const_labels=cl)
        self._m_spec_accept = M.Histogram(
            "paddle_spec_accept_ratio",
            "Accepted draft tokens / proposed (k-1) per speculative "
            "verify",
            const_labels={
                **cl,
                "quant": getattr(self._model, "quant", None) or "f32",
                "mesh": self.mesh_desc},
            buckets=(0.0, 0.25, 0.5, 0.75, 1.0))
        self._instruments = [
            self._m_requests, self._m_tokens, self._m_shed,
            self._m_retired, self._m_deadline, self._m_restarts,
            self._m_compiles, self._m_steps, self._m_ttft,
            self._m_intertoken, self._m_step_exec, self._m_occupancy,
            self._m_active, self._m_queue, self._m_prefix_hits,
            self._m_prefix_misses, self._m_prefix_evictions,
            self._m_shared_pages, self._m_live_pages,
            self._m_spec_accept]
        ref = weakref.ref(self)

        def _collector():
            eng = ref()
            return eng._collect_families() if eng is not None else None

        self._obs_collector = _collector
        obs_metrics.REGISTRY.register_collector(_collector)

    def _collect_families(self):
        with self._lock:
            self._m_queue.set(len(self._pending))
            self._m_active.set(len(self._active))
            shared = self._slots.shared_pages()
            live = self._slots.live_pages()
            if self._draft_slots is not None:
                shared += self._draft_slots.shared_pages()
                live += self._draft_slots.live_pages()
            self._m_shared_pages.set(shared)
            self._m_live_pages.set(live)
            return [m.collect() for m in self._instruments]

    def _prefix_identity(self):
        """Replica identity for persistent prefix-cache keys/headers —
        the same fields a kv-snapshot resume compares (PR 17's skew-
        refusal discipline). Called lazily, OUTSIDE the engine lock
        (the fingerprint has its own lock)."""
        fp = self._programs._fingerprint()
        if fp is None:
            return None
        return {"fingerprint": fp,
                "weights": self._programs._weights_digest(),
                "quant": getattr(self._model, "quant", None) or "f32",
                "mesh": self.mesh_desc}

    # ------------------------------------------------------------ submit
    def submit(self, prompt, max_new_tokens=None, features=(),
               token_budget_s=None, trace_id=None, eos_token_id=None,
               snapshot_every=None, speculative=False):
        """Enqueue one sequence; -> :class:`DecodeRequest`.

        ``prompt``: 1-D (or [1, P]) int32/int64 token ids (the output
        token dtype echoes it). ``features``: per-sequence arrays
        matching the model's ``feature_spec`` (any wire dtype).
        ``token_budget_s``: per-token SLO — bounds time-to-first-token
        and every inter-token gap; a blown budget fails the request
        retryable and frees its slot. ``snapshot_every``: emit a
        resumable kv-snapshot block every N generated tokens
        (``DecodeRequest.take_snapshot``; 0 = never, None = the
        engine's env-configured default). ``speculative``: opt in to
        draft-and-verify decoding (wire 0x5C bit 61) — tokens stay
        bitwise-equal to non-speculative greedy; a no-op on an engine
        without a draft model."""
        chaos.hit("serving.decode.admit")
        prompt = np.asarray(prompt)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt must be a non-empty 1-D token array "
                f"(got shape {tuple(prompt.shape)})")
        if prompt.dtype in _TOKEN_DTYPES:
            # the spec's token-dtype set (wire codes 1/2): streamed
            # chunks echo exactly this dtype back on the wire
            token_dtype = prompt.dtype.type
        else:
            raise ValueError(
                f"prompt dtype {prompt.dtype} is not a token dtype "
                "(int32 / int64)")
        if prompt.size > self.max_prompt_len:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds max_prompt_len="
                f"{self.max_prompt_len}")
        prompt_i32 = np.ascontiguousarray(prompt.astype(np.int32))
        spec = self._model.feature_spec
        features = [np.ascontiguousarray(np.asarray(f)) for f in features]
        if len(features) != len(spec):
            raise ValueError(
                f"model expects {len(spec)} feature array(s), "
                f"got {len(features)}")
        for f, (tr, dt) in zip(features, spec):
            if tuple(f.shape) != tr or f.dtype != dt:
                raise ValueError(
                    f"feature shape/dtype {f.shape}/{f.dtype} does not "
                    f"match spec {tr}/{dt}")
        if max_new_tokens is None:
            max_new_tokens = self.default_max_new_tokens
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eos = (self._model.eos_token_id if eos_token_id is None
               else eos_token_id)
        if trace_id is None:
            trace_id = obs_tracing.current_trace_id()
        req = DecodeRequest(prompt_i32, features, max_new_tokens, eos,
                            token_budget_s, trace_id, token_dtype)
        req.snapshot_every = max(0, int(
            self.default_snapshot_every if snapshot_every is None
            else snapshot_every))
        req.speculative = bool(speculative)
        with self._cond:
            if self._closed:
                raise EngineClosed(f"{self.name} is closed")
            if len(self._pending) >= self.max_queue:
                self._m_shed.inc(reason="queue_full")
                raise EngineOverloaded(
                    f"{self.name} decode queue full "
                    f"({len(self._pending)} waiting, cap {self.max_queue})"
                    "; request shed")
            self._pending.append(req)
            self._m_requests.inc()
            self._cond.notify_all()
        return req

    def generate(self, prompt, timeout=None, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(prompt, **kw).result(timeout)

    def cancel(self, req):
        """Abandon a request: if still queued it is dropped here; if
        running, the scheduler purges its KV slot at the next
        iteration boundary (before any further compute)."""
        req.cancel()
        with self._cond:
            try:
                self._pending.remove(req)
            except ValueError:
                pass  # already joined (or finished); scheduler purges
            self._cond.notify_all()

    # ---------------------------------------------------- stream resume
    def _build_snapshot(self, req, kv_copies, pos, last_token,
                        n_generated):
        """Encode one kv-snapshot block for a running sequence (runs
        OUTSIDE the engine lock: the lazy fingerprint has its own
        lock and must not nest inside ours)."""
        m = self._model
        header = {
            "fingerprint": self._programs._fingerprint(),
            "weights": self._programs._weights_digest(),
            "quant": getattr(m, "quant", None) or "f32",
            "mesh": self.mesh_desc,
            "pos": int(pos),
            "last_token": int(last_token),
            "n_generated": int(n_generated),
            "prompt_len": int(req.prompt.size),
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": req.eos_token_id,
            "n_kv": len(m.kv_spec),
        }
        tail = np.asarray(req.tokens_so_far()[:n_generated],
                          dtype=req.token_dtype)
        arrays = [req.prompt, tail] + list(kv_copies) + list(req.features)
        return _wire_spec.encode_kv_snapshot(header, arrays)

    def _refuse(self, why):
        with self._lock:
            self._n_resumes_refused += 1
        raise SnapshotRefused(f"{self.name}: snapshot refused ({why}); "
                              "resume on a replica matching the "
                              "snapshot's identity")

    def check_snapshot(self, payload):
        """Parse + validate one kv-snapshot block against THIS
        replica's identity and limits; -> (header, arrays).

        Raises ValueError for a malformed or internally inconsistent
        block (permanent — wire status 1) and :class:`SnapshotRefused`
        for an identity or capacity skew (retryable — wire status 2:
        the snapshot is fine, this replica is the wrong home for it).
        The cmd kv_put preflight and :meth:`resume` share this check:
        validation cannot drift from what a resume actually demands."""
        header, arrays, _ = _wire_spec.decode_kv_snapshot_off(payload)
        m = self._model
        pos = int(header["pos"])
        n_gen = int(header["n_generated"])
        plen = int(header["prompt_len"])
        prompt, tail = arrays[0], arrays[1]
        n_kv = int(header.get("n_kv", len(m.kv_spec)))
        if n_gen < 1:
            raise ValueError("kv snapshot carries no generated tokens")
        if prompt.ndim != 1 or prompt.size != plen:
            raise ValueError(
                f"kv snapshot prompt shape {tuple(prompt.shape)} does "
                f"not match its declared prompt_len {plen}")
        if tail.ndim != 1 or tail.size != n_gen:
            raise ValueError(
                f"kv snapshot token tail of {tail.size} does not match "
                f"its declared n_generated {n_gen}")
        if tail.dtype not in _TOKEN_DTYPES:
            raise ValueError(
                f"kv snapshot token tail dtype {tail.dtype} is not a "
                "token dtype (int32 / int64)")
        if pos != plen + n_gen - 1:
            raise ValueError(
                f"kv snapshot position invariant broken: pos {pos} != "
                f"prompt_len {plen} + n_generated {n_gen} - 1")
        if int(header["last_token"]) != int(tail[-1]):
            raise ValueError(
                "kv snapshot last_token does not match its token tail")
        fp = self._programs._fingerprint()
        if header["fingerprint"] != fp:
            self._refuse(f"model fingerprint "
                         f"{header['fingerprint']!r} != {fp!r}")
        wd = self._programs._weights_digest()
        if header["weights"] != wd:
            self._refuse("weights digest mismatch: same architecture, "
                         "different parameter values — a foreign KV "
                         "cache would decode garbage")
        quant = getattr(m, "quant", None) or "f32"
        if header["quant"] != quant:
            self._refuse(f"quant mode {header['quant']!r} != {quant!r}")
        if header["mesh"] != self.mesh_desc:
            self._refuse(f"mesh {header['mesh']!r} != "
                         f"{self.mesh_desc!r}")
        if n_kv != len(m.kv_spec):
            self._refuse(f"{n_kv} kv buffers != this model's "
                         f"{len(m.kv_spec)}")
        if len(arrays) != 2 + n_kv + len(m.feature_spec):
            self._refuse(
                f"{len(arrays)} arrays != prompt + tail + {n_kv} kv + "
                f"{len(m.feature_spec)} features")
        if pos > self.max_seq_len:
            self._refuse(f"position {pos} exceeds this engine's "
                         f"max_seq_len {self.max_seq_len}")
        for a, (tr, dt) in zip(arrays[2:2 + n_kv], m.kv_spec):
            if (a.ndim != 1 + len(tr) or tuple(a.shape[1:]) != tr
                    or a.dtype != dt or a.shape[0] < pos):
                self._refuse(
                    f"kv buffer {tuple(a.shape)}/{a.dtype} does not "
                    f"match kv_spec {tr}/{dt} at position {pos}")
        for f, (tr, dt) in zip(arrays[2 + n_kv:], m.feature_spec):
            if tuple(f.shape) != tr or f.dtype != dt:
                self._refuse(
                    f"feature {tuple(f.shape)}/{f.dtype} does not "
                    f"match feature_spec {tr}/{dt}")
        return header, arrays

    def seed_check(self, payload):
        """cmd kv_put preflight for a prefill->decode handoff: validate
        the block against THIS replica (sharing :meth:`check_snapshot`
        with the resume path) AND confirm the engine can seed a FRESH
        slot for it now; -> (header, arrays).

        A handoff places the sequence before the stream commits, so a
        replica with no free KV slot and a backed-up queue refuses
        retryable here — the router tries the next decode replica —
        instead of absorbing a sequence it cannot start. This is the
        capacity half kv_put adds over a plain resume of a broken
        stream (which already holds its position and must queue)."""
        header, arrays = self.check_snapshot(payload)
        with self._lock:
            if self._closed:
                raise EngineClosed(f"{self.name} is closed")
            waiting = len(self._pending) + len(self._pending_resume)
            if waiting >= self.max_queue:
                self._m_shed.inc(reason="queue_full")
                raise EngineOverloaded(
                    f"{self.name} decode queue full; seed the handoff "
                    "elsewhere")
            if self._slots.free_count() == 0 and waiting > 0:
                self._m_shed.inc(reason="no_free_slot")
                raise EngineOverloaded(
                    f"{self.name} has no free KV slot and {waiting} "
                    "sequences already waiting; seed the handoff "
                    "elsewhere")
        return header, arrays

    def resume(self, snapshot, token_budget_s=None, trace_id=None,
               snapshot_every=None, max_new_tokens=None,
               speculative=False):
        """Resume a snapshotted sequence on THIS engine at its exact
        position; -> :class:`DecodeRequest`.

        The returned request's ``next_tokens`` yields only the tokens
        AFTER the snapshot position (what a resumed wire stream must
        carry) while ``result`` returns the full sequence including
        the snapshot's tail. The join enters the step loop through the
        already-warm (rows, seq) ladder — no new program shapes, so a
        resume costs zero post-warmup compiles — and greedy decode is
        RNG-free, so the suffix is bitwise identical to an unbroken
        solo decode of the same prompt."""
        chaos.hit("serving.decode.resume")
        header, arrays = self.check_snapshot(snapshot)
        m = self._model
        n_kv = int(header.get("n_kv", len(m.kv_spec)))
        prompt, tail = arrays[0], arrays[1]
        kv_arrays = list(arrays[2:2 + n_kv])
        feats = [np.ascontiguousarray(a) for a in arrays[2 + n_kv:]]
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else header.get("max_new_tokens")
                      or self.default_max_new_tokens)
        eos = header.get("eos_token_id")
        pos = int(header["pos"])
        n_gen = int(header["n_generated"])
        last = int(header["last_token"])
        if trace_id is None:
            trace_id = obs_tracing.current_trace_id()
        req = DecodeRequest(np.ascontiguousarray(prompt.astype(np.int32)),
                            feats, max_new, eos, token_budget_s,
                            trace_id, tail.dtype.type)
        req.snapshot_every = max(0, int(
            self.default_snapshot_every if snapshot_every is None
            else snapshot_every))
        req.speculative = bool(speculative)
        # pre-fill the snapshot's tail as already-consumed: result()
        # sees the full sequence, the stream re-emits nothing
        req._tokens = [int(t) for t in tail]
        req._taken = n_gen
        # a snapshot taken AT a stop boundary resumes to an immediate
        # clean finish — never a slot occupied for zero steps
        if eos is not None and last == eos:
            done = "eos"
        elif n_gen >= max_new:
            done = "max_tokens"
        elif pos >= self.max_seq_len:
            done = "max_seq_len"
        else:
            done = None
        if done is not None:
            with self._lock:
                self._n_resumes_ok += 1
            self._m_retired.inc(reason=done)
            req._finish(done)
            return req
        state = {"pos": pos, "last_token": last, "n_generated": n_gen}
        with self._cond:
            if self._closed:
                raise EngineClosed(f"{self.name} is closed")
            if (len(self._pending) + len(self._pending_resume)
                    >= self.max_queue):
                self._m_shed.inc(reason="queue_full")
                raise EngineOverloaded(
                    f"{self.name} decode queue full; resume shed")
            self._pending_resume.append((req, kv_arrays, state))
            self._m_requests.inc()
            self._cond.notify_all()
        return req

    # --------------------------------------------------------- scheduler
    def _run_scheduler(self, gen):
        try:
            self._scheduler_loop(gen)
        except Exception:  # noqa: BLE001 - watchdog owns recovery
            traceback.print_exc()
            if self._watchdog is None:
                self._restart_scheduler(gen, "died (watchdog disabled)")

    def _scheduler_loop(self, gen):
        while True:
            # GIL-atomic monotonic bump, same contract as batching.py
            self._heartbeat = time.monotonic()  # tpu-lint: disable=TPU305  # benign race: GIL-atomic monotonic bump
            joiners = self._wait_for_work(gen)
            if joiners is None:
                return  # closed and drained, or superseded
            chaos.hit("serving.decode.loop")
            if joiners:
                self._prefill(gen, joiners)
            if self._superseded(gen):
                return
            self._purge_blown_budgets(gen)
            if self._active:
                self._decode_step(gen)
            if self._superseded(gen):
                return

    def _superseded(self, gen):
        with self._lock:
            return self._sched_gen != gen or self._closed

    # tpu-resource: acquires=kv_slot releases=kv_slot
    def _join_resumes_locked(self, now):
        """Re-admit resume joiners FIRST (they already paid their
        prefill elsewhere), while slots are free, entirely under the
        caller's ``_cond`` hold — restore is pure host memcpy, so a
        resumed sequence can never be stranded in-flight by a
        scheduler death. The restored slot is owned by the active
        sequence from birth and freed through the normal retire
        paths."""
        while self._pending_resume and self._slots.free_count() > 0:
            req, kv_arrays, st = self._pending_resume.pop(0)
            slot = self._slots.restore(kv_arrays, st["pos"])
            s = _Seq(req, slot, st["pos"], st["last_token"], now)
            s.n_generated = st["n_generated"]
            self._active.append(s)
            self._n_resumes_ok += 1

    def _wait_for_work(self, gen):
        """Park until there is something to do; pop this iteration's
        joiners (bounded by free slots). None = exit this thread."""
        with self._cond:
            while True:
                if self._sched_gen != gen:
                    return None
                now = time.monotonic()
                self._purge_expired_pending_locked(now)
                self._drop_cancelled_locked()
                if self._active or self._pending or self._pending_resume:
                    break
                if self._closed:
                    return None
                self._cond.wait()  # tpu-lint: disable=TPU303  # submit/cancel/close/restart all notify_all under _cond
            self._join_resumes_locked(now)
            joiners = []
            free = self._slots.free_count()
            while self._pending and len(joiners) < free:
                joiners.append(self._pending.pop(0))
            self._inflight_join = joiners
            return joiners

    def _purge_expired_pending_locked(self, now):
        """Per-token SLO on the FIRST token: a queued request whose
        budget already elapsed is purged before any compute (resume
        joiners: the first RESUMED token — same clock, same status)."""
        expired = [r for r in self._pending
                   if r.token_budget_s is not None
                   and now - r.t_enqueue >= r.token_budget_s]
        for r in expired:
            self._pending.remove(r)
            self._m_deadline.inc(stage="expired")
            r._fail(DeadlineExceeded(
                f"{self.name}: per-token budget elapsed before the "
                "sequence could join; dropped without compute"))
        expired_resume = [e for e in self._pending_resume
                          if e[0].token_budget_s is not None
                          and now - e[0].t_enqueue
                          >= e[0].token_budget_s]
        for e in expired_resume:
            self._pending_resume.remove(e)
            self._m_deadline.inc(stage="expired")
            e[0]._fail(DeadlineExceeded(
                f"{self.name}: per-token budget elapsed before the "
                "resumed sequence could join; dropped without compute"))

    def _drop_cancelled_locked(self):
        self._pending[:] = [r for r in self._pending if not r.cancelled]
        self._pending_resume[:] = [e for e in self._pending_resume
                                   if not e[0].cancelled]

    # tpu-resource: releases=kv_slot
    def _purge_blown_budgets(self, gen):
        """Retire active sequences that were cancelled or blew their
        per-token budget — BEFORE the next step, so a dead client's
        slot frees immediately instead of riding the batch to
        max_new_tokens (the slot-leak audit of ISSUE 12). Slot release
        and the active-list update happen under ONE lock acquisition:
        a concurrent watchdog restart releases every active slot, and
        interleaving with it would double-free a slot into the pool."""
        now = time.monotonic()
        purged = []
        with self._lock:
            if self._sched_gen != gen or self._closed:
                # a stale (restarted-away) scheduler must not touch
                # the replacement's active list or free slots it no
                # longer owns — the restart handled every sequence it
                # knew about
                return
            keep = []
            for s in self._active:
                if s.req.cancelled:
                    purged.append((s, "cancelled", None))
                    self._release_seq(s)
                elif (s.req.token_budget_s is not None
                        and now - s.t_last > s.req.token_budget_s):
                    purged.append((s, "deadline", DeadlineExceeded(
                        f"{self.name}: per-token budget "
                        f"{s.req.token_budget_s}s blown after "
                        f"{s.n_generated} tokens; slot purged")))
                    self._release_seq(s)
                else:
                    keep.append(s)
            self._active[:] = keep
        for s, reason, err in purged:
            self._notify_retired(s, reason, err)

    # ----------------------------------------------------------- prefill
    # tpu-resource: acquires=kv_slot releases=kv_slot
    def _prefill(self, gen, joiners):
        """Admit joiners: consult the prefix cache, run the prefill
        program over cache-miss prompts ONLY, install shared pages for
        hits, then feed every joiner's uncached suffix token-by-token
        through the already-warm step rungs. The LAST suffix step is
        the *finishing step* — the last prompt token fed at position
        P-1 — and its logits produce the first emitted token for cold
        and hit joiners alike, so the first token always comes from
        the identical step-shaped computation: prefix-hit-vs-cold
        bitwise equality holds by construction, not by tolerance."""
        plans = []
        for r in joiners:
            plan = {"req": r, "hashes": [], "hit": None, "load": None}
            if self._prefix is not None:
                hashes = prefix_hashes(r.prompt, self._slots.page_len,
                                       feature_seed(r.features))
                plan["hashes"] = hashes
                if hashes:
                    hit = self._prefix.lookup(hashes)
                    if hit is not None:
                        plan["hit"] = hit
                        self._m_prefix_hits.inc()
                    else:
                        self._m_prefix_misses.inc()
                        # persistent tier: file IO, engine lock NOT held
                        plan["load"] = self._prefix.load_store(
                            hashes, r.prompt)
            plans.append(plan)
        cold = [p for p in plans
                if p["hit"] is None and p["load"] is None]
        kv_cold = None
        if cold:
            rows = bucket_rows(max(len(cold), 2), self._rows_cap)
            p_bucket = seq_bucket(max(p["req"].prompt.size for p in cold),
                                  self.min_seq_bucket, self.max_seq_len)
            key = ("prefill", rows, p_bucket)
            if not self._breaker_allows(key, joiners):
                with self._lock:
                    if self._sched_gen == gen and not self._closed:
                        # stale schedulers must not wipe the REPLACEMENT
                        # scheduler's in-flight joiner record
                        self._inflight_join = []
                return
            t0 = time.monotonic()
            try:
                run = self._program(key, warming=False,
                                    trace_id=next(
                                        (r.trace_id for r in joiners
                                         if r.trace_id is not None),
                                        None))
                tokens = np.zeros((rows, p_bucket), np.int32)
                lengths = np.ones((rows,), np.int32)  # pad rows: len 1
                for i, p in enumerate(cold):
                    tokens[i, :p["req"].prompt.size] = p["req"].prompt
                    lengths[i] = p["req"].prompt.size
                batch = [tokens, lengths] + self._feature_batch(
                    [p["req"] for p in cold], rows)
                chaos.hit("serving.decode.prefill")
                outs = run(batch)
            except Exception as e:  # noqa: BLE001 - fail these joiners
                self._record_breaker(key, ok=False)
                err = e if isinstance(e, RetryableError) \
                    else RetryableError(
                        f"{self.name}: prefill failed "
                        f"({type(e).__name__}: {e}); retry the request")
                with self._lock:
                    if self._sched_gen == gen and not self._closed:
                        self._inflight_join = []
                for r in joiners:
                    r._fail(err)
                    self._m_retired.inc(reason="error")
                return
            self._record_breaker(key, ok=True)
            dt = time.monotonic() - t0
            self._m_steps.inc(phase="prefill")
            self._m_step_exec.observe(dt, phase="prefill")
            obs_tracing.observe("serving.decode.prefill", dt)
            kv_cold = outs[1:]
            for i, p in enumerate(cold):
                p["cold_row"] = i
        # --- install: slots alloc + page installs + active-list entry
        # in ONE lock acquisition, so the sequences are restart-visible
        # from the instant they hold slots (a watchdog sweep releases
        # and fails exactly these — no leak window)
        now = time.monotonic()
        admitted = []  # feed state: {"s", "q", "installed", "plan"}
        stale = False
        with self._lock:
            if self._sched_gen != gen or self._closed:
                stale = True
            else:
                self._inflight_join = []
                for p in plans:
                    r = p["req"]
                    P = r.prompt.size
                    # guaranteed non-None: admission was bounded by
                    # the free count
                    slot = self._slots.alloc()
                    if p["hit"] is not None:
                        installed, pages = p["hit"]
                        self._slots.install_shared(slot, pages)
                    elif p["load"] is not None:
                        hx, installed, kv_arrays = p["load"]
                        pages = self._prefix.install_arrays(
                            hx, installed, kv_arrays)
                        self._slots.install_shared(slot, pages)
                    else:
                        installed = P
                        self._slots.write_prefill(
                            slot, [k[p["cold_row"]] for k in kv_cold], P)
                    s = _Seq(r, slot, installed, 0, now)
                    s.n_generated = 0  # nothing emitted until the
                    # finishing step's logits land
                    self._active.append(s)
                    admitted.append({"s": s, "q": min(installed, P - 1),
                                     "installed": installed, "plan": p})
        if stale:
            err = SchedulerRestarted(
                f"{self.name} decode scheduler was restarted while this "
                "sequence was in prefill; retry the request")
            for r in joiners:
                r._fail(err)
            return
        # --- suffix feed: token-by-token through the step ladder,
        # every joiner in one batch (a cold joiner feeds exactly its
        # finishing step; a hit joiner feeds positions c..P-1)
        feed = list(admitted)
        prefill_chaos = not cold  # admissions with zero cold prompts
        # still traverse the prefill chaos site exactly once
        while feed:
            n = len(feed)
            rows = bucket_rows(max(n, 2), self._rows_cap)
            need = max(f["q"] + 1 for f in feed)
            seq_b = seq_bucket(need, self.min_seq_bucket,
                               self.max_seq_len)
            key = ("step", rows, seq_b)
            if not self._breaker_allows(key, [f["s"].req
                                              for f in admitted]):
                self._drop_admitted(gen, admitted)
                return
            t0 = time.monotonic()
            try:
                run = self._program(key, warming=False,
                                    trace_id=next(
                                        (f["s"].req.trace_id
                                         for f in feed
                                         if f["s"].req.trace_id
                                         is not None), None))
                tokens = np.zeros((rows,), np.int32)
                positions = np.zeros((rows,), np.int32)
                for i, f in enumerate(feed):
                    tokens[i] = int(f["s"].req.prompt[f["q"]])
                    positions[i] = f["q"]
                kv = self._slots.gather([f["s"].slot for f in feed],
                                        [f["q"] for f in feed],
                                        rows, seq_b)
                batch = ([tokens, positions] + kv
                         + self._feature_batch(
                             [f["s"].req for f in feed], rows))
                if prefill_chaos:
                    chaos.hit("serving.decode.prefill")
                    prefill_chaos = False
                outs = run(batch)
            except Exception as e:  # noqa: BLE001 - abort the admission
                self._record_breaker(key, ok=False)
                err = e if isinstance(e, RetryableError) \
                    else RetryableError(
                        f"{self.name}: prefix fill failed "
                        f"({type(e).__name__}: {e}); retry the request")
                self._drop_admitted(gen, admitted, err)
                return
            self._record_breaker(key, ok=True)
            dt = time.monotonic() - t0
            self._m_steps.inc(phase="prefix_fill")
            self._m_step_exec.observe(dt, phase="prefix_fill")
            obs_tracing.observe("serving.decode.prefix_fill", dt)
            logits = outs[0]
            entries = outs[1:]
            with self._lock:
                if self._sched_gen != gen or self._closed:
                    return  # restart failed + released the admitted
                for i, f in enumerate(feed):
                    s = f["s"]
                    if f["q"] >= f["installed"]:
                        self._slots.write_entry(s.slot, f["q"],
                                                [e[i] for e in entries])
                    # else: the computed KV row is bitwise equal to the
                    # installed shared page — skip the host write so
                    # COW never clones over an identical value
                    if f["q"] == s.req.prompt.size - 1:
                        f["first"] = int(np.argmax(logits[i]))
                    f["q"] += 1
            feed = [f for f in feed if f["q"] < f["s"].req.prompt.size]
        # --- emit first tokens + cache inserts, one lock acquisition
        now = time.monotonic()
        finished = []  # (seq, reason, err) notified post-lock
        snaps = []     # (seq, kv copies, pos, last, n_gen) — encoded
        # after the lock, same discipline as the step path
        pubs = []      # persistent-tier publishes (file IO, post-lock)
        with self._lock:
            if self._sched_gen != gen or self._closed:
                return  # restart failed + released the admitted
            drop = set()
            for f in admitted:
                s = f["s"]
                r = s.req
                tok = f["first"]
                s.pos = r.prompt.size
                s.last_token = tok
                s.n_generated = 1
                if (r.token_budget_s is not None
                        and now - r.t_enqueue > r.token_budget_s):
                    # the FIRST token is a token too: a blown TTFT
                    # budget fails retryable and frees the slot
                    drop.add(id(s))
                    self._release_seq(s)
                    finished.append((s, "deadline", DeadlineExceeded(
                        f"{self.name}: first token arrived past the "
                        f"per-token budget {r.token_budget_s}s")))
                    continue
                self._m_ttft.observe(now - r.t_enqueue)
                self._emit(s, tok, now, ttft=True)
                # prefill-boundary snapshot (cadence 1 only): the
                # n_generated=1 block IS the prefill->decode handoff
                # format, and it must exist even when the sequence
                # retires right here (a handoff request runs with
                # max_new_tokens=1) — so the kv copies are taken
                # BEFORE the slot can be released
                if r.snapshot_every == 1:
                    snaps.append(
                        (s, self._slots.snapshot(s.slot, s.pos),
                         s.pos, s.last_token, s.n_generated))
                hashes = f["plan"]["hashes"]
                if self._prefix is not None and hashes:
                    # retain EVERY chain boundary (pages are shared
                    # between them, so a shorter shared prefix still
                    # hits); evictions ride the LRU byte budget
                    ev = 0
                    for n_tok, hx in hashes:
                        ev += self._prefix.insert(
                            hx, n_tok,
                            self._slots.export_pages(
                                s.slot,
                                n_tok // self._slots.page_len))
                    if ev:
                        self._m_prefix_evictions.inc(ev)
                    n_tok, hx = hashes[-1]
                    if self._prefix.needs_publish(hx):
                        pubs.append((hx, n_tok, r.prompt,
                                     self._slots.snapshot(s.slot,
                                                          n_tok)))
                reason = self._stop_reason(s)
                if reason is not None:
                    drop.add(id(s))
                    self._release_seq(s)
                    finished.append((s, reason, None))
            if drop:
                self._active[:] = [x for x in self._active
                                   if id(x) not in drop]
        # push snapshots BEFORE retirement notification: _push_snapshot
        # on a finished request is a no-op, and the handoff flow needs
        # the n_generated=1 block of a max_new_tokens=1 sequence
        for s, kv_copies, pos, last, n_gen in snaps:
            try:
                chaos.hit("serving.decode.snapshot")
                s.req._push_snapshot(self._build_snapshot(
                    s.req, kv_copies, pos, last, n_gen), n_gen)
                with self._lock:
                    self._n_snapshots += 1
            except Exception:  # noqa: BLE001 - degraded, never fatal
                # a failed snapshot just means no resume point for this
                # window; the stream itself must keep flowing
                pass
        for hx, n_tok, prompt, kv_copies in pubs:
            try:
                self._prefix.publish(hx, n_tok, prompt, kv_copies)
            except Exception:  # noqa: BLE001 - publish is best-effort
                pass
        for s, reason, err in finished:
            self._notify_retired(s, reason, err)

    # tpu-resource: releases=kv_slot
    def _drop_admitted(self, gen, admitted, err=None):
        """Abort a mid-prefill admission: pull the sequences off the
        active list and free their slots atomically against a restart
        sweep; fail the requests when ``err`` is given (a breaker shed
        already failed them in ``_breaker_allows``)."""
        with self._lock:
            if self._sched_gen != gen or self._closed:
                return  # the restart swept these already
            drop = {id(f["s"]) for f in admitted}
            self._active[:] = [x for x in self._active
                               if id(x) not in drop]
            for f in admitted:
                self._release_seq(f["s"])
        if err is not None:
            for f in admitted:
                self._m_retired.inc(reason="error")
                f["s"].req._fail(err)

    # ------------------------------------------------------- decode step
    def _decode_step(self, gen):
        """One scheduler iteration over the active set: members that
        opted into speculation (and have headroom) take a draft+verify
        burst; everyone else takes one plain step. A draft-side
        failure NEVER fails a request — the speculative group falls
        back to the plain step path for this iteration."""
        active = list(self._active)
        spec, normal = [], []
        for s in active:
            (spec if self._spec_ok(s) else normal).append(s)
        if spec and not self._spec_group(gen, spec):
            normal += spec  # draft fallback: plain-step this iteration
        if normal:
            self._step_group(gen, normal)

    def _spec_ok(self, s):
        """May this sequence take a K-token speculative burst now?
        Needs opt-in, room for K kv entries, and at least 2 tokens of
        budget left (a 1-token tail is cheaper as a plain step)."""
        return (self.spec_enabled and s.req.speculative
                and s.pos + self._spec_k <= self.max_seq_len
                and s.req.max_new_tokens - s.n_generated >= 2)

    # tpu-resource: releases=kv_slot
    def _step_group(self, gen, active):
        n = len(active)
        rows = bucket_rows(max(n, 2), self._rows_cap)
        need = max(s.pos + 1 for s in active)
        seq_b = seq_bucket(need, self.min_seq_bucket, self.max_seq_len)
        key = ("step", rows, seq_b)
        if not self._breaker_allows(key, [s.req for s in active]):
            with self._lock:
                if self._sched_gen == gen and not self._closed:
                    drop = {id(s) for s in active}
                    for s in active:
                        self._release_seq(s)
                    self._active[:] = [x for x in self._active
                                       if id(x) not in drop]
            return
        t0 = time.monotonic()
        try:
            run = self._program(key, warming=False,
                                trace_id=next((s.req.trace_id
                                               for s in active
                                               if s.req.trace_id
                                               is not None), None))
            tokens = np.zeros((rows,), np.int32)
            positions = np.zeros((rows,), np.int32)
            for i, s in enumerate(active):
                tokens[i] = s.last_token
                positions[i] = s.pos
            kv = self._slots.gather([s.slot for s in active],
                                    [s.pos for s in active], rows, seq_b)
            batch = ([tokens, positions] + kv
                     + self._feature_batch([s.req for s in active], rows))
            chaos.hit("serving.decode.step")
            outs = run(batch)
        except Exception as e:  # noqa: BLE001 - fail the whole step batch
            # the step's kv writes never happened (the program raised),
            # but exactly-once token delivery is gone for this batch:
            # fail every member retryable and free the slots — clients
            # retry, parked requests join a healthy next iteration.
            # Release + clear happen atomically with the generation
            # check: a restart that raced us already did both.
            self._record_breaker(key, ok=False)
            err = e if isinstance(e, RetryableError) else RetryableError(
                f"{self.name}: decode step failed "
                f"({type(e).__name__}: {e}); retry the request")
            with self._lock:
                if self._sched_gen != gen or self._closed:
                    return  # restart already failed + released all
                drop = {id(s) for s in active}
                for s in active:
                    self._release_seq(s)
                self._active[:] = [x for x in self._active
                                   if id(x) not in drop]
            for s in active:
                self._m_retired.inc(reason="error")
                s.req._fail(err)
            return
        self._record_breaker(key, ok=True)
        now = time.monotonic()
        dt = now - t0
        self._m_steps.inc(phase="step")
        self._m_step_exec.observe(dt, phase="step")
        self._m_occupancy.observe(n / rows)
        obs_tracing.observe("serving.decode.step", dt)
        logits = outs[0]
        entries = outs[1:]
        finished = []  # (seq, reason, err) — notified after the lock
        snaps = []     # (seq, kv copies, pos, last, n_gen) — encoded
        # after the lock: header assembly touches the fingerprint lock
        # and json, neither of which may nest inside the engine lock
        with self._lock:
            if self._sched_gen != gen or self._closed:
                # superseded mid-step: the restart failed these
                # sequences and released their slots — our results
                # are late zombies and must not touch slot state
                # (_push on a done request is already a no-op)
                return
            # the whole result application is ONE lock acquisition:
            # slot writes/releases and the active-list update can
            # never interleave with a restart's release sweep
            drop = set()
            for i, s in enumerate(active):
                self._slots.write_entry(s.slot, s.pos,
                                        [e[i] for e in entries])
                s.pos += 1
                tok = int(np.argmax(logits[i]))
                s.last_token = tok
                s.n_generated += 1
                # per-token SLO enforced AT EMIT: a token that arrived
                # past the budget is an SLO miss — the client gave up
                # by its own timeout, so fail retryable and free the
                # slot rather than refresh t_last and pretend it was
                # on time
                if (s.req.token_budget_s is not None
                        and now - s.t_last > s.req.token_budget_s):
                    self._release_seq(s)
                    drop.add(id(s))
                    finished.append((s, "deadline", DeadlineExceeded(
                        f"{self.name}: token {s.n_generated} arrived "
                        f"{now - s.t_last:.3f}s after the previous one "
                        f"(per-token budget {s.req.token_budget_s}s); "
                        "slot purged")))
                    continue
                self._emit(s, tok, now)
                reason = self._stop_reason(s)
                if reason is None:
                    if (s.req.snapshot_every
                            and s.n_generated % s.req.snapshot_every
                            == 0):
                        snaps.append(
                            (s, self._slots.snapshot(s.slot, s.pos),
                             s.pos, s.last_token, s.n_generated))
                else:
                    self._release_seq(s)
                    drop.add(id(s))
                    finished.append((s, reason, None))
            if drop:
                self._active[:] = [x for x in self._active
                                   if id(x) not in drop]
        for s, kv_copies, pos, last, n_gen in snaps:
            try:
                chaos.hit("serving.decode.snapshot")
                s.req._push_snapshot(self._build_snapshot(
                    s.req, kv_copies, pos, last, n_gen), n_gen)
                with self._lock:
                    self._n_snapshots += 1
            except Exception:  # noqa: BLE001 - degraded, never fatal
                # a failed snapshot just means no resume point for this
                # window; the stream itself must keep flowing
                pass
        for s, reason, err in finished:
            self._notify_retired(s, reason, err)

    def _token_at(self, s, p):
        """The sequence's REAL token at absolute position ``p`` — the
        draft catch-up feed. Invariant: s.pos = plen + n_generated - 1,
        so positions below plen come from the prompt, s.pos carries
        last_token, and the span between is already-emitted output."""
        plen = s.req.prompt.size
        if p < plen:
            return int(s.req.prompt[p])
        if p == s.pos:
            return int(s.last_token)
        return int(s.req.tokens_so_far()[p - plen])

    # ---------------------------------------------------- speculative
    # tpu-resource: releases=kv_slot
    def _spec_group(self, gen, group):
        """One draft+verify burst for ``group``. Returns False when the
        DRAFT side cannot run (program failure, quarantine) — the
        caller then plain-steps the group, so draft trouble degrades
        throughput, never correctness. A VERIFY-side failure also
        falls back: no engine state mutates until verify results are
        applied host-side under the lock.

        Greedy equivalence: verify feeds [last_token, d_1..d_{K-1}] at
        positions pos..pos+K-1 through K UNROLLED step_fn iterations in
        one program — bitwise-identical per position to K sequential
        step dispatches — and the accept loop enters position j+1 only
        while d_j == argmax(logits_j), so every emitted token and every
        committed kv entry is exactly what non-speculative greedy
        decode would have produced. Rejected-run rollback is simply
        never writing the rejected entries."""
        K = self._spec_k
        # --- draft prefill for members that never drafted before
        fresh = [s for s in group if s.draft_slot is None]
        if fresh:
            rows = bucket_rows(max(len(fresh), 2), self._rows_cap)
            p_bucket = seq_bucket(max(s.req.prompt.size for s in fresh),
                                  self.min_seq_bucket, self.max_seq_len)
            key = ("draft_prefill", rows, p_bucket)
            if not self._breaker_probe(key):
                return False
            t0 = time.monotonic()
            try:
                run = self._program(key, warming=False)
                tokens = np.zeros((rows, p_bucket), np.int32)
                lengths = np.ones((rows,), np.int32)
                for i, s in enumerate(fresh):
                    tokens[i, :s.req.prompt.size] = s.req.prompt
                    lengths[i] = s.req.prompt.size
                batch = [tokens, lengths] + self._feature_batch(
                    [s.req for s in fresh], rows)
                outs = run(batch)
            except Exception:  # noqa: BLE001 - draft is best-effort
                self._record_breaker(key, ok=False)
                return False
            self._record_breaker(key, ok=True)
            self._m_steps.inc(phase="draft_prefill")
            self._m_step_exec.observe(time.monotonic() - t0,
                                      phase="draft_prefill")
            kv = outs[1:]
            with self._lock:
                if self._sched_gen != gen or self._closed:
                    return True  # restart owns the group now
                for i, s in enumerate(fresh):
                    # bounded: one draft slot per active sequence and
                    # the draft pool is sized like the target pool
                    s.draft_slot = self._draft_slots.alloc()
                    self._draft_slots.write_prefill(
                        s.draft_slot, [k[i] for k in kv],
                        s.req.prompt.size)
                    s.draft_pos = s.req.prompt.size
        # --- catch-up + propose: feed the draft model one token per
        # dispatch until every member's draft saw positions
        # 0..pos+K-2; feeds at >= pos come from last_token then the
        # draft's own proposals (the logits of feeds at >= pos ARE the
        # proposals d_1..d_{K-1})
        drafts = {id(s): [] for s in group}
        while True:
            todo = [s for s in group if s.draft_pos < s.pos + K - 1]
            if not todo:
                break
            rows = bucket_rows(max(len(todo), 2), self._rows_cap)
            need = max(s.draft_pos + 1 for s in todo)
            seq_b = seq_bucket(need, self.min_seq_bucket,
                               self.max_seq_len)
            key = ("draft_step", rows, seq_b)
            if not self._breaker_probe(key):
                return False
            feeds = []
            for s in todo:
                p = s.draft_pos
                if p <= s.pos:
                    feeds.append(self._token_at(s, p))
                else:
                    feeds.append(drafts[id(s)][p - s.pos - 1])
            t0 = time.monotonic()
            try:
                run = self._program(key, warming=False)
                tokens = np.zeros((rows,), np.int32)
                positions = np.zeros((rows,), np.int32)
                for i, s in enumerate(todo):
                    tokens[i] = feeds[i]
                    positions[i] = s.draft_pos
                kv = self._draft_slots.gather(
                    [s.draft_slot for s in todo],
                    [s.draft_pos for s in todo], rows, seq_b)
                batch = ([tokens, positions] + kv
                         + self._feature_batch([s.req for s in todo],
                                               rows))
                outs = run(batch)
            except Exception:  # noqa: BLE001 - draft is best-effort
                self._record_breaker(key, ok=False)
                return False
            self._record_breaker(key, ok=True)
            self._m_steps.inc(phase="draft_step")
            self._m_step_exec.observe(time.monotonic() - t0,
                                      phase="draft_step")
            logits = outs[0]
            entries = outs[1:]
            with self._lock:
                if self._sched_gen != gen or self._closed:
                    return True  # restart owns the group now
                for i, s in enumerate(todo):
                    self._draft_slots.write_entry(
                        s.draft_slot, s.draft_pos,
                        [e[i] for e in entries])
                    if s.draft_pos >= s.pos:
                        drafts[id(s)].append(int(np.argmax(logits[i])))
                    s.draft_pos += 1
        # --- verify: ONE batched target program over all K positions
        rows = bucket_rows(max(len(group), 2), self._rows_cap)
        need = max(s.pos + K for s in group)
        seq_b = seq_bucket(need, self.min_seq_bucket, self.max_seq_len)
        key = ("verify", rows, seq_b)
        if not self._breaker_probe(key):
            return False
        t0 = time.monotonic()
        try:
            run = self._program(key, warming=False,
                                trace_id=next((s.req.trace_id
                                               for s in group
                                               if s.req.trace_id
                                               is not None), None))
            tokens = np.zeros((rows, K), np.int32)
            positions = np.zeros((rows,), np.int32)
            for i, s in enumerate(group):
                tokens[i, 0] = s.last_token
                tokens[i, 1:] = drafts[id(s)]
                positions[i] = s.pos
            kv = self._slots.gather([s.slot for s in group],
                                    [s.pos for s in group], rows, seq_b)
            batch = ([tokens, positions] + kv
                     + self._feature_batch([s.req for s in group], rows))
            outs = run(batch)
        except Exception:  # noqa: BLE001 - fall back, requests unharmed
            self._record_breaker(key, ok=False)
            return False
        self._record_breaker(key, ok=True)
        now = time.monotonic()
        dt = now - t0
        self._m_steps.inc(phase="verify")
        self._m_step_exec.observe(dt, phase="verify")
        obs_tracing.observe("serving.decode.verify", dt)
        logits = outs[0]    # (rows, K, vocab)
        entries = outs[1:]  # each (rows, K, ...)
        finished = []
        snaps = []
        with self._lock:
            if self._sched_gen != gen or self._closed:
                return True  # restart owns the group now
            drop = set()
            for i, s in enumerate(group):
                d = drafts[id(s)]
                n0 = s.n_generated
                accepted = 0
                retired = False
                for j in range(K):
                    u = int(np.argmax(logits[i, j]))
                    # iteration j runs only while the fed token at j
                    # is the REAL token (j=0 feeds last_token; j>0
                    # guarded by the d[j-1]==u break below), so this
                    # kv entry is exactly the plain-step entry —
                    # rejected entries are simply never written
                    self._slots.write_entry(s.slot, s.pos,
                                            [e[i, j] for e in entries])
                    s.pos += 1
                    s.last_token = u
                    s.n_generated += 1
                    if j > 0:
                        accepted += 1
                    if (s.req.token_budget_s is not None
                            and now - s.t_last > s.req.token_budget_s):
                        self._release_seq(s)
                        drop.add(id(s))
                        finished.append((s, "deadline",
                                         DeadlineExceeded(
                            f"{self.name}: token {s.n_generated} "
                            f"arrived {now - s.t_last:.3f}s after the "
                            f"previous one (per-token budget "
                            f"{s.req.token_budget_s}s); slot purged")))
                        retired = True
                        break
                    self._emit(s, u, now)
                    reason = self._stop_reason(s)
                    if reason is not None:
                        self._release_seq(s)
                        drop.add(id(s))
                        finished.append((s, reason, None))
                        retired = True
                        break
                    if j + 1 < K and d[j] != u:
                        break  # first rejection ends the burst
                self._m_spec_accept.observe(accepted / (K - 1))
                self._n_spec_iters += 1
                self._n_spec_accepted += accepted
                if retired:
                    continue
                # rollback-by-pointer: draft entries past the accepted
                # run were computed from rejected tokens; the next
                # catch-up overwrites them before they become visible
                s.draft_pos = min(s.draft_pos, s.pos)
                if (s.req.snapshot_every
                        and s.n_generated // s.req.snapshot_every
                        > n0 // s.req.snapshot_every):
                    snaps.append(
                        (s, self._slots.snapshot(s.slot, s.pos),
                         s.pos, s.last_token, s.n_generated))
            if drop:
                self._active[:] = [x for x in self._active
                                   if id(x) not in drop]
        for s, kv_copies, pos, last, n_gen in snaps:
            try:
                chaos.hit("serving.decode.snapshot")
                s.req._push_snapshot(self._build_snapshot(
                    s.req, kv_copies, pos, last, n_gen), n_gen)
                with self._lock:
                    self._n_snapshots += 1
            except Exception:  # noqa: BLE001 - degraded, never fatal
                pass
        for s, reason, err in finished:
            self._notify_retired(s, reason, err)
        return True

    # ----------------------------------------------------------- helpers
    def _feature_batch(self, reqs, rows):
        spec = self._model.feature_spec
        out = [np.zeros((rows,) + tr, dt) for tr, dt in spec]
        for i, r in enumerate(reqs):
            for o, f in zip(out, r.features):
                o[i] = f
        return out

    def _emit(self, s, tok, now, ttft=False):
        gap = now - (s.req.t_enqueue if ttft else s.t_last)
        if not ttft:
            self._m_intertoken.observe(gap)
        s.t_last = now
        self._m_tokens.inc()
        if s.req.trace_id is not None:
            obs_tracing.record_span(
                "serving.decode.token", gap,
                trace_id=s.req.trace_id, engine=self.name,
                index=s.n_generated - 1, first=ttft)
        s.req._push(tok)

    def _stop_reason(self, s):
        """Why this sequence retires now, or None (pure check — the
        caller owns the slot release)."""
        if s.req.eos_token_id is not None \
                and s.last_token == s.req.eos_token_id:
            return "eos"
        if s.n_generated >= s.req.max_new_tokens:
            return "max_tokens"
        if s.pos >= self.max_seq_len:
            return "max_seq_len"
        if s.req.cancelled:
            return "cancelled"
        return None

    def _notify_retired(self, s, reason, err=None):
        """Counters + request completion for a sequence whose slot the
        caller already released. Runs OUTSIDE the engine lock."""
        if reason == "deadline":
            self._m_deadline.inc(stage="late")
        self._m_retired.inc(reason=reason)
        if err is not None:
            s.req._fail(err)
        else:
            s.req._finish(reason)
            if s.req.trace_id is not None:
                obs_tracing.record_span(
                    "serving.decode.request",
                    time.monotonic() - s.req.t_enqueue,
                    trace_id=s.req.trace_id, engine=self.name,
                    tokens=s.n_generated, reason=reason)

    # tpu-resource: releases=kv_slot
    def _release_seq(self, s):
        """Free EVERY slot an active sequence holds (target + draft).
        The single release point for active sequences: keeping the
        exactly-once discipline in one place is what keeps the shared-
        page refcounts balanced across purge / retire / restart /
        close paths. Callers hold the engine lock."""
        self._slots.release(s.slot)
        if s.draft_slot is not None:
            self._draft_slots.release(s.draft_slot)
            s.draft_slot = None

    def _breaker_probe(self, key):
        """Breaker check WITHOUT the fail-fast side effect — for the
        draft/verify ladder, where a quarantined program means 'fall
        back to plain steps', never 'fail the requests'."""
        now = time.monotonic()
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = self._breakers[key] = _Breaker(
                    self.breaker_threshold, self.breaker_cooldown)
            return br.allow(now)

    def _breaker_allows(self, key, reqs):
        """Check/trip the program-key breaker; on shed, fail ``reqs``
        fast with the retryable quarantine status."""
        now = time.monotonic()
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = self._breakers[key] = _Breaker(self.breaker_threshold,
                                                   self.breaker_cooldown)
            allowed = br.allow(now)
            if not allowed:
                br.shed += len(reqs)
                self._m_shed.inc(len(reqs), reason="quarantine")
        if not allowed:
            err = BucketQuarantined(
                f"{self.name} program {key} is quarantined after "
                f"{br.failures} consecutive failures; retry after "
                f"cooldown ({self.breaker_cooldown}s)")
            for r in reqs:
                r._fail(err)
                self._m_retired.inc(reason="error")
        return allowed

    def _record_breaker(self, key, ok):
        now = time.monotonic()
        with self._lock:
            br = self._breakers.get(key)
            if br is not None:
                br.record_success() if ok else br.record_failure(now)

    # ----------------------------------------------------------- programs
    def _program(self, key, warming=False, trace_id=None):
        """Materialize-once per (phase, rows, seq) — the decode twin of
        BatchingEngine._compiled (in-flight event so warmup and the
        scheduler never compile the same key twice). ``draft_*`` phases
        route to the draft model's program set; they share this cache,
        the compile counters, and the breakers under their full key."""
        phase, rows, seq_b = key
        while True:
            with self._lock:
                run = self._cache.get(key)
                if run is not None:
                    return run
                ev = self._compiling.get(key)
                if ev is None:
                    ev = self._compiling[key] = threading.Event()
                    mine = True
                else:
                    mine = False
            if not mine:
                # bounded like batching's cold-compile wait: a wedged
                # owner must fail this caller retryably, not park it
                # forever (the owner's compile may still land and cache
                # the program for the next attempt)
                if not ev.wait(_env_float(
                        "PADDLE_TPU_SERVING_COLD_COMPILE_TIMEOUT", 300.0)):
                    raise RetryableError(
                        f"{self.name}: compile for {key} still in "
                        "flight after the cold-compile timeout; retry")
                continue
            try:
                chaos.hit("serving.decode.compile")
                t0 = time.monotonic()
                if phase.startswith("draft_"):
                    run, source = self._draft_programs.compile(
                        phase[len("draft_"):], rows, seq_b,
                        warming=warming)
                else:
                    run, source = self._programs.compile(
                        phase, rows, seq_b, warming=warming)
            except BaseException:
                with self._lock:
                    self._compiling.pop(key, None)
                ev.set()
                raise
            dt = time.monotonic() - t0
            if trace_id is not None:
                obs_tracing.record_span("serving.decode.compile", dt,
                                        trace_id=trace_id,
                                        engine=self.name, phase=phase,
                                        rows=rows, seq=seq_b,
                                        source=source)
            else:
                obs_tracing.observe("serving.decode.compile", dt)
            with self._lock:
                self._cache[key] = run
                cc = self._compile_counts.setdefault(
                    key, {"inline": 0, "store": 0})
                cc[source] = cc.get(source, 0) + 1
                self._m_compiles.inc(phase=phase, source=source)
                self._compiling.pop(key, None)
            ev.set()
            return run

    def warmup(self, slot_buckets=None, seq_buckets=None,
               prompt_buckets=None):
        """Precompile the program ladder so no sequence pays a compile
        (and, with an artifact store attached, so a fresh replica
        loads the whole ladder with zero inline XLA compiles).
        Defaults: slot buckets = the power-of-2 ladder up to
        ``max_slots``; seq/prompt buckets = the power-of-2 ladder from
        ``min_seq_bucket`` up to ``max_seq_len`` / ``max_prompt_len``.
        Returns the declared (phase, rows, seq) list.

        A phased engine narrows its default ladder to its pool's hot
        programs: a ``prefill`` engine warms the full prompt ladder but
        only the smallest step bucket (its sequences stop at the first
        token; the residual step ladder exists solely for degraded
        colocated traffic), a ``decode`` engine warms the full step
        ladder but only the smallest prompt bucket (its sequences
        arrive as KV snapshots that already paid prefill elsewhere).
        Explicit bucket arguments always win."""
        def ladder(lo, hi):
            out, b = [], lo
            while b < hi:
                out.append(b)
                b <<= 1
            out.append(hi)
            return sorted(set(out))

        if slot_buckets is None:
            # the runtime floors every dispatch at 2 rows (gemm
            # regime), so the declared ladder starts there too — a
            # max_slots=1 engine runs its one sequence at rows=2
            slot_buckets = ladder(2, self._rows_cap)
        if seq_buckets is None:
            # a prefill-phase engine still runs the suffix-feed /
            # finishing step through the step ladder, so its step
            # rungs must reach the prompt bucket (not just the
            # smallest one)
            seq_buckets = (
                ladder(self.min_seq_bucket,
                       seq_bucket(self.max_prompt_len,
                                  self.min_seq_bucket, self.max_seq_len))
                if self.phase == "prefill"
                else ladder(self.min_seq_bucket, self.max_seq_len))
        if prompt_buckets is None:
            prompt_buckets = (
                [self.min_seq_bucket] if self.phase == "decode"
                else ladder(
                    self.min_seq_bucket,
                    seq_bucket(self.max_prompt_len, self.min_seq_bucket,
                               self.max_seq_len)))
        declared = []
        for rows in slot_buckets:
            rows = bucket_rows(int(rows), self._rows_cap)
            for sb in seq_buckets:
                declared.append(("step", rows,
                                 seq_bucket(int(sb), self.min_seq_bucket,
                                            self.max_seq_len)))
            for pb in prompt_buckets:
                declared.append(("prefill", rows,
                                 seq_bucket(int(pb), self.min_seq_bucket,
                                            self.max_seq_len)))
            if self.spec_enabled:
                # the speculative rungs: K-token verify + the draft
                # model's own step/prefill ladders — all plain
                # (phase, rows, seq) ArtifactKeys, warmed exactly
                # like the base ladder
                for sb in ladder(self.min_seq_bucket, self.max_seq_len):
                    declared.append(("verify", rows, sb))
                    declared.append(("draft_step", rows, sb))
                for pb in ladder(
                        self.min_seq_bucket,
                        seq_bucket(self.max_prompt_len,
                                   self.min_seq_bucket,
                                   self.max_seq_len)):
                    declared.append(("draft_prefill", rows, pb))
        declared = sorted(set(declared))
        for key in declared:
            self._program(key, warming=True)
        with self._lock:
            self._declared = declared
        return declared

    # -------------------------------------------------------------- views
    def stats(self):
        """Engine counters (merged into the cmd-5 ``stats`` wire view).
        One lock acquisition: never a torn snapshot."""
        with self._lock:
            programs = {}
            for key, cc in sorted(self._compile_counts.items()):
                phase, rows, seq_b = key
                d = {"compiles": cc.get("inline", 0),
                     "store_loads": cc.get("store", 0)}
                br = self._breakers.get(key)
                if br is not None:
                    d["breaker"] = br.as_dict()
                programs[f"{phase}{rows}x{seq_b}"] = d
            return {
                "name": self.name,
                "phase": self.phase,
                "quant": getattr(self._model, "quant", None) or "f32",
                "mesh": self.mesh_desc,
                "max_slots": self.max_slots,
                "max_seq_len": self.max_seq_len,
                "max_queue": self.max_queue,
                "active": len(self._active),
                "queue_depth": len(self._pending),
                "requests": int(self._m_requests.value()),
                "tokens": int(self._m_tokens.value()),
                "shed_count": int(self._m_shed.value(reason="queue_full")),
                "quarantine_shed": int(
                    self._m_shed.value(reason="quarantine")),
                "deadline_expired": int(
                    self._m_deadline.value(stage="expired")),
                "deadline_late": int(
                    self._m_deadline.value(stage="late")),
                "scheduler_restarts": int(self._m_restarts.value()),
                "snapshots": self._n_snapshots,
                "resume_queue_depth": len(self._pending_resume),
                "resumes": {"ok": self._n_resumes_ok,
                            "refused": self._n_resumes_refused},
                "retired": {r: int(self._m_retired.value(reason=r))
                            for r in _RETIRE_REASONS},
                "prefills": int(self._m_steps.value(phase="prefill")),
                "steps": int(self._m_steps.value(phase="step")),
                "prefix_fill_steps": int(
                    self._m_steps.value(phase="prefix_fill")),
                "prefix": (self._prefix.stats()
                           if self._prefix is not None else None),
                "shared_pages": (
                    self._slots.shared_pages()
                    + (self._draft_slots.shared_pages()
                       if self._draft_slots is not None else 0)),
                "live_pages": (
                    self._slots.live_pages()
                    + (self._draft_slots.live_pages()
                       if self._draft_slots is not None else 0)),
                "spec": {
                    "enabled": self.spec_enabled,
                    "k": self._spec_k,
                    "iterations": self._n_spec_iters,
                    "accepted": self._n_spec_accepted,
                    "verify_steps": int(
                        self._m_steps.value(phase="verify")),
                    "draft_steps": int(
                        self._m_steps.value(phase="draft_step")),
                    "draft_prefills": int(
                        self._m_steps.value(phase="draft_prefill")),
                },
                "compiles": sum(cc.get("inline", 0)
                                for cc in self._compile_counts.values()),
                "store_loads": sum(cc.get("store", 0)
                                   for cc in self._compile_counts.values()),
                "declared_programs": len(self._declared),
                "programs": programs,
            }

    def health(self):
        now = time.monotonic()
        store_stats = self._programs.store_stats()
        with self._lock:
            alive = self._scheduler.is_alive()
            quarantined = sorted(
                f"{k[0]}{k[1]}x{k[2]}" for k, br in self._breakers.items()
                if br.state != _Breaker.CLOSED)
            return {
                "ok": alive and not self._closed,
                "closed": self._closed,
                "phase": self.phase,
                "scheduler_alive": alive,
                "heartbeat_age_s": round(now - self._heartbeat, 3),
                "scheduler_restarts": int(self._m_restarts.value()),
                "active": len(self._active),
                "free_slots": self._slots.free_count(),
                "queue_depth": len(self._pending),
                "quarantined_programs": quarantined,
                "declared_programs": len(self._declared),
                "mesh": self.mesh_desc,
                "artifact_store": store_stats,
                "prefix_entries": (self._prefix.stats()["entries"]
                                   if self._prefix is not None else 0),
                "spec_enabled": self.spec_enabled,
            }

    # ----------------------------------------------------------- watchdog
    def _run_watchdog(self):
        """Restart a dead or wedged scheduler: active sequences fail
        retryable (their step state is owner-bound; a client retry
        re-decodes from the prompt), parked requests stay queued and
        are served by the replacement — same contract as the one-shot
        engine's watchdog."""
        while not self._closed_ev.wait(self.watchdog_interval):
            with self._lock:
                if self._closed:
                    return
                gen = self._sched_gen
                th = self._scheduler
                hb = self._heartbeat
                head = self._pending[0] if self._pending else None
                active = list(self._active)
            now = time.monotonic()
            dead = not th.is_alive()
            if head is not None:
                oldest = head.t_enqueue
            elif active:
                oldest = min(s.t_last for s in active)
            else:
                oldest = None
            wedged = (oldest is not None
                      and now - hb > self.wedge_timeout
                      and now - oldest > self.wedge_timeout)
            if dead:
                self._restart_scheduler(gen, "died")
            elif wedged:
                self._restart_scheduler(gen, "wedged (heartbeat stale)")

    # tpu-resource: releases=kv_slot
    def _restart_scheduler(self, observed_gen, reason):
        with self._cond:
            if self._closed or observed_gen != self._sched_gen:
                return
            self._sched_gen += 1
            gen = self._sched_gen
            stranded = list(self._active)
            self._active[:] = []
            stranded_join = list(self._inflight_join)
            self._inflight_join = []
            for s in stranded:
                # refcount-aware sweep: pages shared with the prefix
                # cache (or other survivors) are DECREMENTED here,
                # never freed out from under their other holders
                self._release_seq(s)
            self._m_restarts.inc()
            self._heartbeat = time.monotonic()
            t = threading.Thread(target=self._run_scheduler, args=(gen,),
                                 name=f"{self.name}-scheduler-g{gen}",
                                 daemon=True)
            self._scheduler = t
            # start INSIDE the lock: close() must never join an
            # unstarted thread (same rationale as batching.py)
            t.start()  # tpu-lint: disable=TPU304  # load-bearing: close() must never join an unstarted thread
            self._cond.notify_all()
        if stranded or stranded_join:
            err = SchedulerRestarted(
                f"{self.name} decode scheduler {reason} and was "
                "restarted; this sequence was mid-decode — its tokens "
                "so far were delivered but no more will come; retry the "
                "request")
            for s in stranded:
                self._m_retired.inc(reason="error")
                s.req._fail(err)
            for r in stranded_join:
                self._m_retired.inc(reason="error")
                r._fail(err)

    # -------------------------------------------------------------- close
    # tpu-resource: releases=kv_slot
    def close(self, timeout=5.0):
        """Stop the scheduler. Active sequences fail retryable (a
        close mid-stream is a shed, not silent truncation); queued
        requests fail retryable too; new submissions raise
        EngineClosed."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._closed_ev.set()
            pending = list(self._pending)
            self._pending[:] = []
            pending += [e[0] for e in self._pending_resume]
            self._pending_resume[:] = []
            active = list(self._active)
            self._active[:] = []
            for s in active:
                self._release_seq(s)
            if self._prefix is not None:
                # drop the cache's page references AFTER the active
                # sweep so every kv page's refcount walks to zero
                self._prefix.clear()
            self._cond.notify_all()
            sched = self._scheduler
        obs_metrics.REGISTRY.unregister_collector(self._obs_collector)
        err = EngineClosed(f"{self.name} is closing; retry elsewhere")
        for r in pending:
            r._fail(err)
        for s in active:
            s.req._fail(err)
        sched.join(timeout)
        if self._watchdog is not None:
            self._watchdog.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
