"""Predictor — the AnalysisPredictor analog (reference:
paddle/fluid/inference/api/analysis_predictor.cc: Init -> load program ->
OptimizeInferenceProgram -> PrepareExecutor(NaiveExecutor) -> Run /
ZeroCopyRun:860; handle API paddle_api.h ZeroCopyTensor).

TPU-native: "load program" = deserialize StableHLO (jax.export) saved by
``paddle.jit.save``; "analysis passes + NaiveExecutor" = XLA compile of
that module, cached per input-shape signature; "ZeroCopyRun" = inputs
stay device-resident between copy_from_cpu and run, outputs are fetched
lazily by copy_to_cpu.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .config import Config, PrecisionType


class Tensor:
    """Input/output handle (reference: ZeroCopyTensor, paddle_api.h)."""

    def __init__(self, name, role, predictor):
        self._name = name
        self._role = role  # "input" | "output"
        self._pred = predictor
        self._shape = None

    def name(self):
        return self._name

    def reshape(self, shape):
        self._shape = tuple(int(s) for s in shape)

    def copy_from_cpu(self, arr):
        if self._role != "input":
            raise RuntimeError(f"{self._name} is an output handle")
        arr = np.asarray(arr)
        if self._shape is not None:
            arr = arr.reshape(self._shape)
        # device upload happens here, once — run() consumes the resident copy
        self._pred._inputs[self._name] = jax.device_put(arr, self._pred._device)

    def share_external_data(self, arr):
        """Adopt an already-device-resident (or numpy) array without copy."""
        if self._role != "input":
            raise RuntimeError(f"{self._name} is an output handle")
        self._pred._inputs[self._name] = (
            arr if isinstance(arr, jax.Array) else jnp.asarray(arr))

    def copy_to_cpu(self):
        if self._role != "output":
            raise RuntimeError(f"{self._name} is an input handle")
        out = self._pred._outputs.get(self._name)
        if out is None:
            raise RuntimeError("run() has not produced outputs yet")
        return np.asarray(out)

    def shape(self):
        src = (self._pred._inputs if self._role == "input"
               else self._pred._outputs)
        a = src.get(self._name)
        if a is not None:
            return list(a.shape)
        return list(self._shape) if self._shape else []

    def type(self):
        src = (self._pred._inputs if self._role == "input"
               else self._pred._outputs)
        a = src.get(self._name)
        return str(a.dtype) if a is not None else "unknown"


class Predictor:
    """reference: AnalysisPredictor. Load once, run many; clone() shares the
    loaded module + weights and gets its own input/output slots (the
    reference's thread-sharing pattern, analysis_predictor.cc Clone)."""

    def __init__(self, config, _shared=None):
        self._config = config
        if _shared is not None:
            (self._layer, self._in_names, self._out_names, self._device) = _shared
        else:
            from ..jit import load as jit_load

            prefix = config.model_prefix()
            self._layer = jit_load(prefix)
            self._in_names = [f"x{i}"
                              for i in range(len(self._layer._input_specs))]
            self._out_names = None  # discovered at first run
            self._device = self._pick_device()
            # commit weights to the chosen device once; run() then never
            # re-transfers the parameter set (ZeroCopyRun property)
            self._layer.to_device(self._device)
        self._inputs = {}
        self._outputs = {}

    # ----------------------------------------------------------- internals
    def _pick_device(self):
        kind = "cpu" if not self._config.use_gpu() else None
        devs = jax.devices()
        if kind == "cpu":
            cpus = [d for d in devs if d.platform == "cpu"]
            if cpus:
                return cpus[0]
        return devs[min(self._config.gpu_device_id(), len(devs) - 1)]

    # Note on precision: it is a compile/save-time property under XLA — a
    # serialized StableHLO module has fixed input avals, so runtime input
    # casting would be rejected by exported.call. bf16/int8 serving comes
    # from saving the model under amp.auto_cast / quantization instead; the
    # Config knob is kept for introspection only.

    # ----------------------------------------------------------- handle API
    def get_input_names(self):
        return list(self._in_names)

    def get_output_names(self):
        if self._out_names is None:
            return []
        return list(self._out_names)

    def get_input_handle(self, name):
        if name not in self._in_names:
            raise KeyError(f"unknown input {name!r}; inputs: {self._in_names}")
        return Tensor(name, "input", self)

    def get_input_tensor(self, name):  # 1.x spelling
        return self.get_input_handle(name)

    def get_output_handle(self, name):
        if self._out_names is not None and name not in self._out_names:
            raise KeyError(
                f"unknown output {name!r}; outputs: {self._out_names}")
        return Tensor(name, "output", self)

    def get_output_tensor(self, name):
        return self.get_output_handle(name)

    # ----------------------------------------------------------- run
    def run(self, inputs=None):
        """ZeroCopyRun analog. With `inputs` (list of numpy arrays) behaves
        like the reference's Run(feed) convenience; otherwise consumes
        handles set via copy_from_cpu."""
        if inputs is not None:
            if len(inputs) != len(self._in_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model has "
                    f"{len(self._in_names)}: {self._in_names}")
            for name, arr in zip(self._in_names, inputs):
                self.get_input_handle(name).copy_from_cpu(arr)
        missing = [n for n in self._in_names if n not in self._inputs]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        args = [self._inputs[n] for n in self._in_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        arrays = [o._value if hasattr(o, "_value") else o for o in outs]
        if self._out_names is None:
            self._out_names = [f"out{i}" for i in range(len(arrays))]
        self._outputs = dict(zip(self._out_names, arrays))
        if inputs is not None:
            return [np.asarray(a) for a in arrays]
        return True

    def clone(self):
        shared = (self._layer, self._in_names, self._out_names, self._device)
        return Predictor(self._config, _shared=shared)

    def clear_intermediate_tensor(self):
        self._outputs = {}

    def try_shrink_memory(self):
        self._inputs = {}
        self._outputs = {}


def create_predictor(config):
    """reference: CreatePaddlePredictor / paddle_infer::CreatePredictor."""
    if not isinstance(config, Config):
        raise TypeError("create_predictor expects an inference.Config")
    return Predictor(config)
