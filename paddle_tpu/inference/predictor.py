"""Predictor — the AnalysisPredictor analog (reference:
paddle/fluid/inference/api/analysis_predictor.cc: Init -> load program ->
OptimizeInferenceProgram -> PrepareExecutor(NaiveExecutor) -> Run /
ZeroCopyRun:860; handle API paddle_api.h ZeroCopyTensor).

TPU-native: "load program" = deserialize StableHLO (jax.export) saved by
``paddle.jit.save``; "analysis passes + NaiveExecutor" = XLA compile of
that module, cached per input-shape signature; "ZeroCopyRun" = inputs
stay device-resident between copy_from_cpu and run, outputs are fetched
lazily by copy_to_cpu.
"""
import threading
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from .config import Config, PrecisionType

# Engine attach/detach is rare (once per process, not per request) but
# clones are explicitly multithreaded: one process-wide lock keeps two
# threads from each building + warming an engine and leaking the loser.
_ENGINE_ATTACH_LOCK = threading.Lock()


class Tensor:
    """Input/output handle (reference: ZeroCopyTensor, paddle_api.h)."""

    def __init__(self, name, role, predictor):
        self._name = name
        self._role = role  # "input" | "output"
        self._pred = predictor
        self._shape = None

    def name(self):
        return self._name

    def reshape(self, shape):
        self._shape = tuple(int(s) for s in shape)

    def copy_from_cpu(self, arr):
        if self._role != "input":
            raise RuntimeError(f"{self._name} is an output handle")
        arr = np.asarray(arr)
        if self._shape is not None:
            arr = arr.reshape(self._shape)
        if self._pred.batching_engine() is not None:
            # the engine pads + concatenates requests on host and uploads
            # the coalesced batch itself; uploading here would make run()
            # pay a blocking device->host readback per request just to
            # hand the engine the bytes it already had
            self._pred._inputs[self._name] = arr
            return
        # device upload happens here, once — run() consumes the resident copy
        self._pred._inputs[self._name] = jax.device_put(arr, self._pred._device)

    def share_external_data(self, arr):
        """Adopt an already-device-resident (or numpy) array without copy."""
        if self._role != "input":
            raise RuntimeError(f"{self._name} is an output handle")
        self._pred._inputs[self._name] = (
            arr if isinstance(arr, jax.Array) else jnp.asarray(arr))

    def copy_to_cpu(self):
        if self._role != "output":
            raise RuntimeError(f"{self._name} is an input handle")
        out = self._pred._outputs.get(self._name)
        if out is None:
            raise RuntimeError("run() has not produced outputs yet")
        return np.asarray(out)

    def shape(self):
        src = (self._pred._inputs if self._role == "input"
               else self._pred._outputs)
        a = src.get(self._name)
        if a is not None:
            return list(a.shape)
        return list(self._shape) if self._shape else []

    def type(self):
        src = (self._pred._inputs if self._role == "input"
               else self._pred._outputs)
        a = src.get(self._name)
        return str(a.dtype) if a is not None else "unknown"


class Predictor:
    """reference: AnalysisPredictor. Load once, run many; clone() shares the
    loaded module + weights and gets its own input/output slots (the
    reference's thread-sharing pattern, analysis_predictor.cc Clone)."""

    def __init__(self, config, _shared=None):
        self._config = config
        if _shared is not None:
            (self._layer, self._in_names, self._out_names, self._device) = _shared
        else:
            from ..jit import load as jit_load

            prefix = config.model_prefix()
            self._layer = jit_load(prefix)
            self._in_names = [f"x{i}"
                              for i in range(len(self._layer._input_specs))]
            self._out_names = None  # discovered at first run
            self._device = self._pick_device()
            # commit weights to the chosen device once; run() then never
            # re-transfers the parameter set (ZeroCopyRun property)
            self._layer.to_device(self._device)
        self._inputs = {}
        self._outputs = {}

    # ----------------------------------------------------------- internals
    def _pick_device(self):
        kind = "cpu" if not self._config.use_gpu() else None
        devs = jax.devices()
        if kind == "cpu":
            cpus = [d for d in devs if d.platform == "cpu"]
            if cpus:
                return cpus[0]
        return devs[min(self._config.gpu_device_id(), len(devs) - 1)]

    # Note on precision: it is a compile/save-time property under XLA — a
    # serialized StableHLO module has fixed input avals, so runtime input
    # casting would be rejected by exported.call. bf16/int8 serving comes
    # from saving the model under amp.auto_cast / quantization instead; the
    # Config knob is kept for introspection only.

    # ----------------------------------------------------------- handle API
    def get_input_names(self):
        return list(self._in_names)

    def get_output_names(self):
        if self._out_names is None:
            return []
        return list(self._out_names)

    def get_input_handle(self, name):
        if name not in self._in_names:
            raise KeyError(f"unknown input {name!r}; inputs: {self._in_names}")
        return Tensor(name, "input", self)

    def get_input_tensor(self, name):  # 1.x spelling
        return self.get_input_handle(name)

    def get_output_handle(self, name):
        if self._out_names is not None and name not in self._out_names:
            raise KeyError(
                f"unknown output {name!r}; outputs: {self._out_names}")
        return Tensor(name, "output", self)

    def get_output_tensor(self, name):
        return self.get_output_handle(name)

    # ----------------------------------------------------------- batching
    def enable_dynamic_batching(self, engine=None, max_batch_size=None,
                                max_wait_ms=None, max_queue=None,
                                warmup=True, warmup_buckets=None):
        """Route this predictor's run() through a shared dynamic-batching
        engine (inference/batching.py). The engine lives on the loaded
        layer, which clone() shares — so every clone coalesces into ONE
        scheduler instead of racing separate dispatches. Knob defaults
        come from the Config (enable_dynamic_batching /
        enable_tensorrt_engine(max_batch_size=...)). Returns the engine.
        """
        from .batching import BatchingEngine

        prev = prev_owned = None
        with _ENGINE_ATTACH_LOCK:
            if engine is not None:
                # caller-owned engine (possibly shared with a server):
                # attach only — disable_dynamic_batching will detach
                # without closing it. An engine WE built earlier must be
                # closed now or its scheduler thread + compiled programs
                # leak with no handle left to close them.
                prev = getattr(self._layer, "_batch_engine", None)
                prev_owned = getattr(self._layer, "_batch_engine_owned",
                                     False)
                self._layer._batch_engine = engine
                self._layer._batch_engine_owned = False
        if engine is not None:
            if prev is not None and prev is not engine and prev_owned:
                prev.close()
            return engine
        with _ENGINE_ATTACH_LOCK:
            engine = getattr(self._layer, "_batch_engine", None)
            if engine is not None:
                if any(k is not None for k in (max_batch_size, max_wait_ms,
                                               max_queue)):
                    warnings.warn(
                        "enable_dynamic_batching: an engine is already "
                        "attached to this (shared) layer; the knobs passed "
                        "here are ignored. Call disable_dynamic_batching() "
                        "first to rebuild with new settings.",
                        RuntimeWarning, stacklevel=2)
                return engine
            db = self._config.dynamic_batching_config()
            kw = dict(
                # Config.max_batch_size() already encodes the
                # dynamic_batching > tensorrt > 1 precedence
                max_batch_size=(max_batch_size
                                or max(self._config.max_batch_size(), 1)),
                max_wait_ms=(max_wait_ms if max_wait_ms is not None
                             else db.get("max_wait_ms", 2.0)),
                max_queue=(max_queue if max_queue is not None
                           else db.get("max_queue", 256)),
            )
            # robustness knobs recorded on the Config (breaker /
            # watchdog); absent keys fall back to the engine's
            # PADDLE_TPU_SERVING_* env defaults
            for k in ("breaker_threshold", "breaker_cooldown",
                      "watchdog_interval", "wedge_timeout",
                      "cold_compile_timeout"):
                if k in db:
                    kw[k] = db[k]
            engine = BatchingEngine.for_layer(self._layer, **kw)
            if warmup:
                # a clone racing this attach must block until ONE fully
                # warmed engine is published, not build (and compile) a
                # second engine for the same layer
                engine.warmup(warmup_buckets)  # tpu-lint: disable=TPU302  # intentional warmup under the attach lock
            self._layer._batch_engine = engine
            self._layer._batch_engine_owned = True
            return engine

    def disable_dynamic_batching(self):
        """Detach the shared engine; run() goes back to direct dispatch
        for this predictor AND its clones. Engines this predictor built
        are closed; a caller-provided engine is only detached (other
        consumers, e.g. a PredictorServer, may still be using it)."""
        with _ENGINE_ATTACH_LOCK:
            engine = getattr(self._layer, "_batch_engine", None)
            if engine is None:
                return
            owned = getattr(self._layer, "_batch_engine_owned", True)
            self._layer._batch_engine = None
            self._layer._batch_engine_owned = False
        if owned:
            engine.close()

    def batching_engine(self):
        return getattr(self._layer, "_batch_engine", None)

    # ----------------------------------------------------------- run
    def run(self, inputs=None):
        """ZeroCopyRun analog. With `inputs` (list of numpy arrays) behaves
        like the reference's Run(feed) convenience; otherwise consumes
        handles set via copy_from_cpu. With dynamic batching enabled the
        rows go through the shared engine (padded shape-bucket batches,
        outputs sliced back — bitwise-identical to direct dispatch for
        >= 2-row requests, see inference/batching.py)."""
        engine = getattr(self._layer, "_batch_engine", None)
        if inputs is not None:
            if len(inputs) != len(self._in_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model has "
                    f"{len(self._in_names)}: {self._in_names}")
            if engine is not None:
                arrays = engine.infer([np.asarray(a) for a in inputs])
                # keep the handle API coherent with the non-engine
                # path: inputs stay readable/re-runnable afterwards
                for name, arr in zip(self._in_names, inputs):
                    self._inputs[name] = np.asarray(arr)
                if self._out_names is None:
                    self._out_names = [f"out{i}"
                                       for i in range(len(arrays))]
                self._outputs = dict(zip(self._out_names, arrays))
                return arrays
            for name, arr in zip(self._in_names, inputs):
                self.get_input_handle(name).copy_from_cpu(arr)
        missing = [n for n in self._in_names if n not in self._inputs]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        args = [self._inputs[n] for n in self._in_names]
        if engine is not None:
            # np.asarray is free for host arrays (copy_from_cpu keeps
            # them on host while an engine is attached); only
            # share_external_data device arrays pay a readback here
            arrays = engine.infer([np.asarray(a) for a in args])
        else:
            # a host array can be left behind by copy_from_cpu if the
            # engine was detached since; commit it to our device now
            args = [a if isinstance(a, jax.Array)
                    else jax.device_put(a, self._device) for a in args]
            out = self._layer(*args)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            arrays = [o._value if hasattr(o, "_value") else o for o in outs]
        if self._out_names is None:
            self._out_names = [f"out{i}" for i in range(len(arrays))]
        self._outputs = dict(zip(self._out_names, arrays))
        if inputs is not None:
            return [np.asarray(a) for a in arrays]
        return True

    def clone(self):
        shared = (self._layer, self._in_names, self._out_names, self._device)
        return Predictor(self._config, _shared=shared)

    def clear_intermediate_tensor(self):
        self._outputs = {}

    def try_shrink_memory(self):
        self._inputs = {}
        self._outputs = {}


def create_predictor(config):
    """reference: CreatePaddlePredictor / paddle_infer::CreatePredictor."""
    if not isinstance(config, Config):
        raise TypeError("create_predictor expects an inference.Config")
    return Predictor(config)
