"""Inference Config (reference: paddle/fluid/inference/api/paddle_analysis_config.h
AnalysisConfig — model paths, device selection, optimization switches).

Most reference knobs steer the C++ analysis/IR pipeline or vendor engines
(TensorRT, Lite, MKLDNN); under XLA those are compiler decisions, so the
corresponding setters are accepted-and-recorded no-ops kept for API
compatibility. The knobs that matter on TPU: device choice, precision
(bf16 autocast at compile), and donation (memory optim).
"""
import enum
import os


class PrecisionType(enum.Enum):
    Float32 = 0
    Int8 = 1
    Half = 2
    Bfloat16 = 3


class PlaceType(enum.Enum):
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kNPU = 3
    kTPU = 4


class Config:
    """reference: AnalysisConfig (paddle_analysis_config.h)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        # paddle.jit.save writes <prefix>.pdmodel/.pdiparams; Config accepts
        # either a directory containing one model or the explicit pair.
        self._model_dir = None
        self._prog_file = None
        self._params_file = None
        if model_dir is not None and prog_file is None:
            if os.path.isdir(model_dir):
                self._model_dir = model_dir
            else:
                # treat as prefix (the 2.x convention)
                self._prog_file = model_dir + ".pdmodel"
                self._params_file = model_dir + ".pdiparams"
        if prog_file is not None:
            self._prog_file = prog_file
            self._params_file = params_file or os.path.splitext(prog_file)[0] + ".pdiparams"
        self._device = "tpu"
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._memory_optim = True
        self._ir_optim = True
        self._cpu_math_threads = 1
        self._enable_profile = False
        self._exec_stream = None
        self._extra = {}

    # ----------------------------------------------------------- model path
    def set_model(self, prog_or_dir, params_file=None):
        if params_file is None and os.path.isdir(prog_or_dir):
            self._model_dir = prog_or_dir
        else:
            self._prog_file = prog_or_dir
            self._params_file = params_file

    def set_prog_file(self, path):
        self._prog_file = path

    def set_params_file(self, path):
        self._params_file = path

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def model_prefix(self):
        """Resolve the jit.save prefix this config points at."""
        if self._prog_file:
            base = self._prog_file
            if base.endswith(".pdmodel"):
                base = base[: -len(".pdmodel")]
            return base
        if self._model_dir:
            for fn in sorted(os.listdir(self._model_dir)):
                if fn.endswith(".pdmodel"):
                    return os.path.join(self._model_dir, fn[: -len(".pdmodel")])
            raise FileNotFoundError(f"no .pdmodel under {self._model_dir}")
        raise ValueError("Config has no model path; call set_model()")

    # ----------------------------------------------------------- devices
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU request maps onto the accelerator place (TPU here).
        self._device = "tpu"
        self._device_id = device_id

    def enable_tpu(self, device_id=0):
        self._device = "tpu"
        self._device_id = device_id

    def enable_xpu(self, l3_workspace_size=0xFFFFFF):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def gpu_device_id(self):
        return self._device_id

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    def cpu_math_library_num_threads(self):
        return self._cpu_math_threads

    # ----------------------------------------------------------- optimization
    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        self._memory_optim = bool(flag)

    def memory_optim_enabled(self):
        return self._memory_optim

    def switch_use_feed_fetch_ops(self, flag=False):
        self._extra["use_feed_fetch_ops"] = bool(flag)

    def switch_specify_input_names(self, flag=True):
        self._extra["specify_input_names"] = bool(flag)

    # TensorRT/Lite/MKLDNN: vendor-engine capture is XLA's job on TPU; the
    # precision argument is honored (bf16/int8-weight autocast) and
    # max_batch_size caps the dynamic-batching engine
    # (Predictor.enable_dynamic_batching) — the rest is recorded for
    # introspection (reference: enable_tensorrt_engine, EnableLiteEngine,
    # EnableMKLDNN in paddle_analysis_config.h).
    def enable_tensorrt_engine(self, workspace_size=1 << 30, max_batch_size=1,
                               min_subgraph_size=3,
                               precision_mode=PrecisionType.Float32,
                               use_static=False, use_calib_mode=False):
        self._precision = precision_mode
        self._extra["tensorrt"] = dict(workspace_size=workspace_size,
                                       max_batch_size=max_batch_size)

    def tensorrt_engine_enabled(self):
        return "tensorrt" in self._extra

    # ------------------------------------------------------ dynamic batching
    def enable_dynamic_batching(self, max_batch_size=32, max_wait_ms=2.0,
                                max_queue=256, breaker_threshold=None,
                                breaker_cooldown=None,
                                watchdog_interval=None,
                                wedge_timeout=None,
                                cold_compile_timeout=None):
        """Record dynamic-batching engine knobs; Predictor reads them in
        enable_dynamic_batching(). max_batch_size here wins over the
        enable_tensorrt_engine one when both are set. The robustness
        knobs (breaker_threshold/breaker_cooldown for poisoned-bucket
        quarantine, watchdog_interval/wedge_timeout for scheduler
        self-healing — raise wedge_timeout above the model's longest
        legitimate batch execute) default to the PADDLE_TPU_SERVING_*
        env knobs when None."""
        cfg = dict(max_batch_size=int(max_batch_size),
                   max_wait_ms=float(max_wait_ms), max_queue=int(max_queue))
        if breaker_threshold is not None:
            cfg["breaker_threshold"] = int(breaker_threshold)
        if breaker_cooldown is not None:
            cfg["breaker_cooldown"] = float(breaker_cooldown)
        if watchdog_interval is not None:
            cfg["watchdog_interval"] = float(watchdog_interval)
        if wedge_timeout is not None:
            cfg["wedge_timeout"] = float(wedge_timeout)
        if cold_compile_timeout is not None:
            cfg["cold_compile_timeout"] = float(cold_compile_timeout)
        self._extra["dynamic_batching"] = cfg

    def dynamic_batching_enabled(self):
        return "dynamic_batching" in self._extra

    def dynamic_batching_config(self):
        return dict(self._extra.get("dynamic_batching") or {})

    def max_batch_size(self):
        """The serving engine's batch cap: the explicit dynamic-batching
        knob, else the enable_tensorrt_engine(max_batch_size=...) value
        (no longer a TensorRT no-op on TPU), else 1."""
        db = self._extra.get("dynamic_batching")
        if db:
            return int(db["max_batch_size"])
        trt = self._extra.get("tensorrt")
        if trt:
            return int(trt["max_batch_size"])
        return 1

    def enable_lite_engine(self, precision_mode=PrecisionType.Float32,
                           zero_copy=False, passes_filter=(), ops_filter=()):
        self._precision = precision_mode
        self._extra["lite"] = True

    def lite_engine_enabled(self):
        return bool(self._extra.get("lite"))

    def enable_mkldnn(self):
        self._extra["mkldnn"] = True

    def mkldnn_enabled(self):
        return bool(self._extra.get("mkldnn"))

    def set_precision(self, precision):
        self._precision = precision

    def precision(self):
        return self._precision

    # ----------------------------------------------------------- misc
    def enable_profile(self):
        self._enable_profile = True

    def is_valid(self):
        try:
            self.model_prefix()
            return True
        except (ValueError, FileNotFoundError):
            return False

    def summary(self):
        """reference: AnalysisConfig::Summary()."""
        rows = [
            ("model_prefix", self.model_prefix() if self.is_valid() else "<unset>"),
            ("device", f"{self._device}:{self._device_id}"),
            ("precision", self._precision.name),
            ("ir_optim", self._ir_optim),
            ("memory_optim", self._memory_optim),
        ]
        width = max(len(k) for k, _ in rows)
        return "\n".join(f"{k.ljust(width)}  {v}" for k, v in rows)
