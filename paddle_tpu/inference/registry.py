"""Replica registry for the fleet tier (ROADMAP item 3).

One registry per router process. ``serve_model`` replicas are
*registered* (by the Fleet supervisor that spawned them, or manually
for replicas managed elsewhere) and then *heartbeated*: a single
background thread polls each replica's ``health`` wire command (cmd 3)
— the JSON the server already exposes, read over a fresh short-lived
connection so the replica's serving hot path never grows a new lock —
and folds the reply into a per-replica view the router's routing
decision reads:

- ``queue_depth`` / ``declared_buckets``: load and bucket warmth for
  least-loaded, warmth-preferring replica selection;
- ``accepting`` / ``draining_deadline_s`` (absent on old replicas =
  accepting): a draining replica stops receiving NEW work but is not
  poisoned — its in-flight requests finish (zero-drop reload /
  scale-down);
- liveness: a replica whose heartbeat fails ``eject_misses`` times in
  a row — or that the router reports a connection error / timeout on —
  is POISONED (ejected): no routing, no traffic. After
  ``probe_cooldown`` seconds the next heartbeat acts as the single
  half-open probe (the PR 5 circuit-breaker shape): success readmits,
  failure re-ejects and restarts the cooldown.

Chaos site: ``fleet.heartbeat`` fires once per replica probe, so tests
and ``bench.py fleet`` can deterministically fail/delay heartbeats.

Env knobs (constructor kwargs win):
    PADDLE_TPU_FLEET_HEARTBEAT_S       probe period          (0.25)
    PADDLE_TPU_FLEET_EJECT_MISSES     consecutive heartbeat
                                       failures to eject      (2)
    PADDLE_TPU_FLEET_PROBE_COOLDOWN_S  eject -> first probe   (1.0)
    PADDLE_TPU_FLEET_DIAL_TIMEOUT_S    probe connect/read cap (2.0)
"""
import json
import os
import socket
import struct
import threading
import time

from ..obs import metrics as obs_metrics
from ..resilience import chaos
from .wire_spec import CMD_HEALTH, REPLICA_PHASES, STATUS_OK

# replica lifecycle (the eject/readmit state machine)
OK = "ok"            # routable
DRAINING = "draining"  # alive, accepting=false: no NEW work
EJECTED = "ejected"  # poisoned: no routing until a probe succeeds
PROBING = "probing"  # cooldown over; next heartbeat is the probe

_STATES = (OK, DRAINING, EJECTED, PROBING)


# the env-override parsing the resilience layer already has; router.py
# and fleet.py import these FROM HERE so the fleet tier has one home
# for its knob plumbing
from ..resilience.retry import _env_float, _env_int  # noqa: E402,F401


_M_HEARTBEATS = obs_metrics.counter(
    "paddle_fleet_heartbeats_total",
    "Replica heartbeat probes, by result",
    labelnames=("result",))
_M_EJECTS = obs_metrics.counter(
    "paddle_fleet_ejects_total",
    "Replica ejections (poisoned by heartbeat misses or router I/O "
    "errors)")
_M_READMITS = obs_metrics.counter(
    "paddle_fleet_readmits_total",
    "Replicas readmitted by a successful half-open probe")
_M_REPLICAS = obs_metrics.gauge(
    "paddle_fleet_replicas",
    "Registered replicas by state",
    labelnames=("state",))


class ReplicaView:
    """Immutable-ish routing snapshot of one replica (what
    ``ReplicaRegistry.snapshot()`` hands the router)."""

    __slots__ = ("rid", "host", "port", "state", "queue_depth",
                 "warm_buckets", "inflight", "draining_deadline_s",
                 "heartbeat_age_s", "pid", "metrics_port", "phase",
                 "free_slots")

    def __init__(self, rid, host, port, state, queue_depth, warm_buckets,
                 inflight, draining_deadline_s, heartbeat_age_s, pid,
                 metrics_port=None, phase="both", free_slots=None):
        self.rid = rid
        self.host = host
        self.port = port
        self.state = state
        self.queue_depth = queue_depth
        self.warm_buckets = warm_buckets
        self.inflight = inflight
        self.draining_deadline_s = draining_deadline_s
        self.heartbeat_age_s = heartbeat_age_s
        self.pid = pid
        self.metrics_port = metrics_port
        # pool membership (wire_spec.REPLICA_PHASES): registered intent,
        # refreshed from the replica's own health body once it reports
        self.phase = phase
        # decode free KV slots from the last health probe (None until a
        # decode engine reports) — the router's decode-placement signal
        self.free_slots = free_slots

    def as_dict(self):
        return {s: getattr(self, s) for s in self.__slots__}


class _Replica:
    """Mutable registry record. Every field is guarded by the
    registry's single lock — probes and routing I/O happen OUTSIDE it
    on local snapshots."""

    def __init__(self, rid, host, port, pid=None, metrics_port=None,
                 phase="both"):
        self.rid = rid
        self.host = host
        self.port = port
        self.pid = pid  # for supervisors that respawn subprocesses
        self.phase = phase  # pool membership (prefill | decode | both)
        self.free_slots = None  # decode KV slots free at last probe
        # the replica's /metrics HTTP endpoint (obs.httpd.MetricsServer
        # reports the ephemeral port it bound as `.port`) so scrapers
        # can discover the whole fleet from the registry
        self.metrics_port = metrics_port
        self.state = OK
        self.misses = 0
        # True only for ROUTER-initiated drains (set_draining): sticky
        # until the router lifts it. A drain the replica itself
        # announced (cmd 8 / stop()) clears as soon as its health says
        # accepting again — without this bit the two cases are
        # indistinguishable and an undrained replica could stay
        # unroutable forever.
        self.drain_hold = False
        self.queue_depth = 0
        self.warm_buckets = 0
        self.inflight = 0  # router-held in-flight requests
        self.draining_deadline_s = None
        self.ejected_at = None  # monotonic of the last ejection
        self.last_heartbeat = None  # monotonic of the last OK probe


def _probe_health(host, port, timeout):
    """One health probe: fresh connection, cmd 3, parse the JSON.
    Raises OSError/ConnectionError/TimeoutError on a dead replica."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(struct.pack("<IB", 1, CMD_HEALTH))
        hdr = b""
        while len(hdr) < 4:
            chunk = s.recv(4 - len(hdr))
            if not chunk:
                raise ConnectionError("peer closed during health probe")
            hdr += chunk
        (blen,) = struct.unpack("<I", hdr)
        body = b""
        while len(body) < blen:
            chunk = s.recv(blen - len(body))
            if not chunk:
                raise ConnectionError("peer closed during health probe")
            body += chunk
    if not body or body[0] != STATUS_OK:
        raise ConnectionError(f"health probe returned status "
                              f"{body[0] if body else 'empty'}")
    return json.loads(body[1:].decode("utf-8"))


class ReplicaRegistry:
    """Thread-safe replica table + one heartbeat thread (started on
    construction, stopped by :meth:`close`)."""

    def __init__(self, heartbeat_interval=None, eject_misses=None,
                 probe_cooldown=None, dial_timeout=None,
                 probe_fn=_probe_health):
        self.heartbeat_interval = (
            heartbeat_interval if heartbeat_interval is not None
            else _env_float("PADDLE_TPU_FLEET_HEARTBEAT_S", 0.25))
        self.eject_misses = (
            eject_misses if eject_misses is not None
            else _env_int("PADDLE_TPU_FLEET_EJECT_MISSES", 2))
        self.probe_cooldown = (
            probe_cooldown if probe_cooldown is not None
            else _env_float("PADDLE_TPU_FLEET_PROBE_COOLDOWN_S", 1.0))
        self.dial_timeout = (
            dial_timeout if dial_timeout is not None
            else _env_float("PADDLE_TPU_FLEET_DIAL_TIMEOUT_S", 2.0))
        self._probe_fn = probe_fn
        self._lock = threading.Lock()
        self._replicas = {}
        self._closed = threading.Event()
        self._thread = None
        if self.heartbeat_interval > 0:
            self._thread = threading.Thread(
                target=self._heartbeat_loop, name="fleet-heartbeat",
                daemon=True)
            self._thread.start()
        obs_metrics.REGISTRY.register_collector(self._collect)

    # --------------------------------------------------------- membership
    def register(self, rid, host, port, pid=None, metrics_port=None,
                 phase="both"):
        """Add (or re-add after a respawn) a replica. A re-registered
        rid starts fresh: OK state, zero misses. ``metrics_port`` is
        the replica's /metrics HTTP endpoint (advertise the ephemeral
        port ``obs.httpd.MetricsServer`` bound). ``phase`` is the pool
        the replica was spawned into (wire_spec.REPLICA_PHASES); the
        replica's own health body overrides it once probes land."""
        if phase not in REPLICA_PHASES:
            raise ValueError(f"unknown replica phase {phase!r} "
                             f"(expected one of {REPLICA_PHASES})")
        with self._lock:
            self._replicas[rid] = _Replica(rid, str(host), int(port),
                                           pid=pid,
                                           metrics_port=metrics_port,
                                           phase=phase)

    def deregister(self, rid):
        with self._lock:
            self._replicas.pop(rid, None)

    def endpoints(self):
        with self._lock:
            return {r.rid: (r.host, r.port)
                    for r in self._replicas.values()}

    # ------------------------------------------------------------ routing
    def snapshot(self):
        """All replicas as :class:`ReplicaView` rows (every state —
        the router filters; the autoscaler and supervisor want the
        ejected ones too)."""
        now = time.monotonic()
        with self._lock:
            return [ReplicaView(
                r.rid, r.host, r.port, r.state, r.queue_depth,
                r.warm_buckets, r.inflight, r.draining_deadline_s,
                (None if r.last_heartbeat is None
                 else round(now - r.last_heartbeat, 3)), r.pid,
                r.metrics_port, r.phase, r.free_slots)
                for r in self._replicas.values()]

    def routable(self, phase=None):
        """Replicas the router may send NEW work to, least-loaded
        first: OK state, ordered by (router in-flight + last reported
        queue depth, colder-first warmth tie-break inverted — warmer
        replicas win a tie because their bucket ladder is compiled).

        ``phase`` narrows to ONE pool of a disaggregated fleet
        (replicas whose phase matches exactly — "both" replicas serve
        the phase-blind default but belong to neither pure pool).
        Decode placement sorts most-free-KV-slots first instead:
        prefill cares about warm prompt buckets, decode about where a
        resumed sequence can actually get a slot."""
        with self._lock:
            rows = [ReplicaView(
                r.rid, r.host, r.port, r.state, r.queue_depth,
                r.warm_buckets, r.inflight, r.draining_deadline_s,
                None, r.pid, r.metrics_port, r.phase, r.free_slots)
                for r in self._replicas.values()
                if r.state == OK and (phase is None or r.phase == phase)]
        if phase == "decode":
            rows.sort(key=lambda v: (
                -(v.free_slots if v.free_slots is not None else 0),
                v.inflight + v.queue_depth, v.rid))
        else:
            rows.sort(key=lambda v: (v.inflight + v.queue_depth,
                                     -v.warm_buckets, v.rid))
        return rows

    def acquire(self, rid):
        """Router bookkeeping: one more in-flight request on `rid`."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None:
                r.inflight += 1

    def release(self, rid):
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None and r.inflight > 0:
                r.inflight -= 1

    def inflight(self, rid):
        with self._lock:
            r = self._replicas.get(rid)
            return 0 if r is None else r.inflight

    # ------------------------------------------------------ state changes
    def report_io_error(self, rid):
        """Router saw a connection error / timeout talking to `rid`:
        poison it immediately (don't wait for heartbeat misses)."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None or r.state == EJECTED:
                return
            r.state = EJECTED
            r.ejected_at = time.monotonic()
            r.misses = 0
        _M_EJECTS.inc()

    def report_ok(self, rid):
        """Router completed a request on `rid` (any wire status): the
        replica is alive even if its heartbeat is lagging."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None and r.state == OK:
                r.misses = 0

    def set_draining(self, rid, draining=True):
        """Router-side drain mark (no wire round-trip needed): stop
        routing new work to `rid`. STICKY — the heartbeat keeps
        probing it but only ``set_draining(rid, False)`` (or death ->
        EJECTED) moves it out of DRAINING. Replica-announced drains
        (health accepting=false with no router hold) clear themselves
        on the next accepting heartbeat."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.drain_hold = bool(draining)
            if draining and r.state in (OK, PROBING):
                r.state = DRAINING
            elif not draining and r.state == DRAINING:
                r.state = OK
                r.misses = 0

    # ---------------------------------------------------------- heartbeat
    def heartbeat_once(self):
        """One full probe round (the loop body; tests call it
        directly). Probes run OUTSIDE the lock and CONCURRENTLY (one
        short-lived thread per target) — a dead replica burning its
        full dial timeout must not delay detecting the next one;
        results fold back in under the lock."""
        with self._lock:
            now = time.monotonic()
            targets = []
            for r in self._replicas.values():
                if r.state == EJECTED:
                    if (r.ejected_at is None
                            or now - r.ejected_at >= self.probe_cooldown):
                        r.state = PROBING  # one half-open probe
                    else:
                        continue  # still cooling down: no traffic at all
                targets.append((r.rid, r.host, r.port, r.state))
        if not targets:
            return
        if len(targets) == 1:
            self._probe_one(*targets[0])
            return
        threads = [threading.Thread(target=self._probe_one, args=t,
                                    name=f"fleet-probe-{t[0]}",
                                    daemon=True) for t in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.dial_timeout + 2.0)

    def _probe_one(self, rid, host, port, state):
        try:
            chaos.hit(f"fleet.heartbeat.{rid}")
            chaos.hit("fleet.heartbeat")
            health = self._probe_fn(host, port, self.dial_timeout)
        except (OSError, ConnectionError, TimeoutError, ValueError):
            self._heartbeat_miss(rid, state)
            _M_HEARTBEATS.inc(result="miss")
        except Exception:  # noqa: BLE001 — an exotic probe failure
            # (chaos-armed RuntimeError, JSON of the wrong shape) is
            # still just a miss, never a dead heartbeat thread
            self._heartbeat_miss(rid, state)
            _M_HEARTBEATS.inc(result="miss")
        else:
            self._heartbeat_ok(rid, state, health)
            _M_HEARTBEATS.inc(result="ok")

    def _heartbeat_ok(self, rid, probed_state, health):
        accepting = bool(health.get("accepting",
                                    not health.get("draining", False)))
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.misses = 0
            r.last_heartbeat = time.monotonic()
            r.queue_depth = int((health.get("engine") or {})
                                .get("queue_depth", 0))
            r.warm_buckets = len((health.get("engine") or {})
                                 .get("declared_buckets") or [])
            r.draining_deadline_s = health.get("draining_deadline_s")
            # the replica's own phase declaration wins over what the
            # supervisor registered (a reconfigured replica re-pools
            # itself on its next heartbeat); unknown values are ignored
            # so a newer replica can't poison routing
            phase = health.get("phase")
            if phase in REPLICA_PHASES:
                r.phase = phase
            free = (health.get("decode") or {}).get("free_slots")
            r.free_slots = int(free) if free is not None else None
            readmitted = False
            if r.state == PROBING:
                # the half-open probe succeeded: readmit (into
                # DRAINING while a drain is announced or held)
                r.state = (OK if accepting and not r.drain_hold
                           else DRAINING)
                readmitted = True
            elif r.state in (OK, DRAINING):
                # replica-announced drains (cmd 8 / stop()) flip here
                # in BOTH directions without router action; a
                # router-initiated drain (set_draining) holds DRAINING
                # until the router lifts it — drain_hold keeps a stale
                # not-accepting probe that raced an undrain from
                # parking the replica out of routing forever
                if not accepting:
                    r.state = DRAINING
                    r.ejected_at = None
                elif r.state == DRAINING and not r.drain_hold:
                    r.state = OK
                    r.misses = 0
        if readmitted:
            _M_READMITS.inc()

    def _heartbeat_miss(self, rid, probed_state):
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            if r.state == PROBING:
                # failed half-open probe: back to a full cooldown
                r.state = EJECTED
                r.ejected_at = time.monotonic()
                return
            r.misses += 1
            if r.misses >= self.eject_misses and r.state in (OK, DRAINING):
                r.state = EJECTED
                r.ejected_at = time.monotonic()
                ejected = True
            else:
                ejected = False
        if ejected:
            _M_EJECTS.inc()

    def _heartbeat_loop(self):
        while not self._closed.wait(self.heartbeat_interval):
            try:
                self.heartbeat_once()
            except Exception:  # noqa: BLE001 — heartbeat must survive
                # a single bad round (e.g. chaos-injected) must not
                # kill the thread: the next tick retries
                pass

    def _collect(self):
        # refresh the (already-registered) state gauge at scrape time;
        # return [] so the family is not rendered twice
        with self._lock:
            counts = {s: 0 for s in _STATES}
            for r in self._replicas.values():
                counts[r.state] += 1
        for s, n in counts.items():
            _M_REPLICAS.set(n, state=s)
        return []

    # -------------------------------------------------------------- close
    def close(self):
        self._closed.set()
        obs_metrics.REGISTRY.unregister_collector(self._collect)
        if self._thread is not None:
            self._thread.join(5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
