"""Dynamic-batching serving engine: coalesce concurrent infer requests
into padded shape-bucket batches over one compiled-program cache.

On TPU, serving throughput comes almost entirely from batch parallelism
and from amortizing XLA compilation over stable shapes — a
thread-per-request predictor pays full dispatch per sample and a full
compile per novel shape. This engine is the runtime complement to
tracelint's static recompilation-hazard passes (TPU101-TPU104):

  requests --> bounded queue --> scheduler thread --> padded bucket batch
                (load shed,       (fire on max_batch_size                 \
                 deadline purge)   or max_wait_ms)                         --> per-bucket
                                        ^                                      AOT-compiled
  response <-- slice rows off <---------|--------------------------------- program
                                   watchdog thread
                              (heartbeat check, restart)

Shape buckets are powers of two (clamped to ``max_batch_size``): padding
the coalesced row count up to the next bucket means each bucket's
program compiles exactly once, no matter what request mix arrives.
Declared buckets are precompiled by :meth:`BatchingEngine.warmup` so the
first real request never eats a compile. The bounded queue plus
:class:`EngineOverloaded` (wire status ``2``) turn saturation into fast
rejection — load shedding — instead of unbounded memory growth.

Graceful degradation (at production scale, *recovering* from component
failure — not avoiding it — is what preserves throughput):

- **Scheduler watchdog**: the scheduler bumps a heartbeat each loop; a
  watchdog thread restarts a dead or wedged scheduler, failing only the
  in-flight group with a retryable status (:class:`SchedulerRestarted`,
  wire status 2) — parked requests are served by the restarted
  scheduler, never stranded.
- **Poisoned-bucket quarantine**: N consecutive compile/execute failures
  for one (bucket, signature) trip a circuit breaker — that bucket sheds
  fast (:class:`BucketQuarantined`, wire status 2) while other buckets
  keep serving; after a cooldown one half-open probe group re-admits it.
- **Deadlines**: a request may carry an absolute deadline; expired
  requests are purged *before* dispatch (no compute for a client that
  already gave up) and a group never waits past the tightest deadline of
  its members.
- **Chaos sites**: ``serving.scheduler.loop``, ``serving.compile[.bucketN]``,
  ``serving.execute[.bucketN]`` and ``serving.submit`` let the
  deterministic chaos harness (resilience/chaos.py) inject scheduler
  death, poisoned buckets, and mid-batch failures in CI (the artifact
  store adds ``artifact.get`` / ``artifact.verify`` / ``artifact.put``
  / ``artifact.put.publish``).
- **Sharded serving** (inference/sharding.py, opt-in via
  ``mesh="tp2"`` / ``PADDLE_TPU_SERVING_MESH``): weights commit to a
  device mesh once at load and every bucket program becomes a
  per-(bucket, mesh) pjit program — models bigger than one chip's HBM
  serve behind the same engine, wire-transparently (README "Sharded
  serving" has the determinism contract per mesh).
- **Persistent artifact store** (serialize/artifact_store.py, opt-in
  via ``PADDLE_TPU_ARTIFACT_DIR``): warmup and cold buckets consult a
  crash-safe on-disk store of exported programs before compiling —
  a fresh replica, hot reload, or restart warms its whole bucket
  ladder with zero XLA compiles, and any corrupt/torn/skewed artifact
  degrades to the inline compile it would have done anyway. Warmup is
  single-flight across processes: N replicas warming one bucket pay
  ONE compile fleet-wide.

Telemetry (paddle_tpu/obs): the engine's counters are obs.metrics
instruments — cmd-5 ``stats`` and cmd-3 ``health`` are consistent views
over them (read under one engine-lock acquisition) and the process
registry exposes the same instruments to Prometheus (wire cmd 6 and
``serve_model(metrics_port=)``). Per-request spans cover
enqueue -> batch -> (compile) -> execute, tagged with the
wire-propagated trace id (``infer(trace_id=...)``), and every AOT
bucket compile lands in the compile ledger (``obs.LEDGER``) with its
cost-analysis FLOPs and structural HLO fingerprint — the data
``bench.py perfproxy`` gates on.

Env knobs (constructor kwargs override):
    PADDLE_TPU_SERVING_BREAKER_THRESHOLD   consecutive failures to trip
                                           a bucket breaker (default 3;
                                           0 disables the breaker)
    PADDLE_TPU_SERVING_BREAKER_COOLDOWN    seconds an open breaker waits
                                           before its half-open probe
                                           (default 5.0)
    PADDLE_TPU_SERVING_WATCHDOG_INTERVAL   heartbeat check period
                                           (default 0.5; 0 disables the
                                           watchdog)
    PADDLE_TPU_SERVING_WEDGE_TIMEOUT       heartbeat staleness (with work
                                           pending) treated as a wedged
                                           scheduler (default 30.0)

Determinism contract (verified in tests/test_serving_batching.py):
engine outputs are bitwise identical to unbatched ``Predictor.run`` for
any request of >= 2 rows and for all integer dtypes — padding rows are
sliced off before anything is returned, and XLA's row-independent
programs are bitwise row-stable across batch sizes >= 2 on CPU. The one
carve-out: XLA lowers batch-1 float matmuls to a gemv with different
rounding than the gemm used for batch >= 2, so a COALESCED 1-row float
request can differ from its solo baseline in the last ulp (a solo 1-row
request fires at bucket 1 — the same program as the baseline — and stays
bitwise equal). A 1-row tail chunk of a split oversized request pads to
bucket 2 for the same reason: its rows came from a >= 2-row baseline
dispatch, so it must stay in the gemm regime.
"""
import json
import os
import threading
import time
import traceback
import warnings
import weakref

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from ..obs.ledger import LEDGER
from ..resilience import chaos
from ..resilience.retry import _env_float, _env_int
from ..serialize import artifact_store as _artifacts
from ..serialize.export import deserialize_exported, serialize_exported
from . import sharding as _sharding

# Machine-checked lock order (tools/tracelint.py --concurrency, TPU309):
# the engine lock is the SUBSYSTEM lock; obs instrument and registry
# locks nest strictly inside it, never the reverse — otherwise metrics
# exposition could deadlock the serving hot path. These declarations
# turn the prose invariant from obs/metrics.py's docstring into a
# gated check.
# tpu-lock-order: BatchingEngine._lock < Metric._lock  # subsystem -> instrument
# tpu-lock-order: BatchingEngine._lock < Registry._lock  # collectors run OUTSIDE the registry lock

# Wire status byte for a shed request, from the machine-readable
# protocol spec (wire_spec is import-light: the engine still has no
# import-time dependency on the server).
from .wire_spec import STATUS_RETRYABLE as OVERLOADED_STATUS  # noqa: E402


class RetryableError(RuntimeError):
    """Transient serving failure: the caller should back off and retry
    (the server maps every subclass to wire status 2)."""

    status_code = OVERLOADED_STATUS


class EngineOverloaded(RetryableError):
    """Raised by submit/infer when the bounded queue is full: the caller
    should back off (the server maps this to wire status 2)."""


class SchedulerRestarted(RetryableError):
    """The scheduler died or wedged while this request's group was in
    flight; the watchdog restarted it. A dead scheduler never delivered
    the group's results; a wedged one may still be executing it — either
    way the results are discarded, never delivered, so retrying cannot
    observe a double answer (a wedge-triggered retry can, however,
    re-run rows the stuck execute eventually finishes — inference is
    side-effect free, so duplicate compute, not duplicate effects)."""


class BucketQuarantined(RetryableError):
    """This request's (bucket, signature) breaker is open after repeated
    compile/execute failures; the bucket sheds fast while it cools down.
    Other buckets keep serving."""


class DeadlineExceeded(RetryableError):
    """The request's deadline passed before its batch dispatched; it was
    dropped without spending compute (the client already gave up)."""


class EngineClosed(RuntimeError):
    pass


def bucket_rows(n, max_batch_size):
    """Next power-of-2 >= n, clamped to max_batch_size."""
    if n <= 0:
        raise ValueError(f"need at least one row, got {n}")
    if n >= max_batch_size:
        return max_batch_size
    return min(max_batch_size, 1 << (n - 1).bit_length())


def _signature(arrays):
    """Batch-compatibility key: dtype + trailing dims of every input
    (requests coalesce only when everything but the row count matches)."""
    return tuple((a.dtype.str, a.shape[1:]) for a in arrays)


class _Request:
    __slots__ = ("inputs", "rows", "sig", "event", "outputs", "error",
                 "t_enqueue", "min_bucket", "deadline", "trace_id")

    def __init__(self, inputs, rows, sig, min_bucket=1, deadline=None,
                 trace_id=None):
        self.inputs = inputs
        self.rows = rows
        self.sig = sig
        self.event = threading.Event()
        self.outputs = None
        self.error = None
        self.t_enqueue = time.monotonic()
        # split chunks of a >= 2-row request carry min_bucket=2: a solo
        # 1-row tail chunk must still fire in the batch >= 2 regime
        # (bucket 1 is XLA's gemv regime, which rounds differently) to
        # keep the split path bitwise equal to the unbatched baseline
        self.min_bucket = min_bucket
        # absolute time.monotonic() drop-dead point (None = no deadline)
        self.deadline = deadline
        # wire-propagated trace id (obs.tracing): spans recorded for
        # this request's enqueue/execute carry it
        self.trace_id = trace_id

    def fail(self, error):
        """Deliver an error result unless a result already landed."""
        if not self.event.is_set():
            self.error = error
            self.event.set()


class _BucketStats:
    __slots__ = ("compiles", "store_loads", "batches", "requests", "rows",
                 "padded_rows", "total_ms", "max_ms")

    def __init__(self):
        self.compiles = 0  # real inline XLA compiles only
        self.store_loads = 0  # programs deserialized from the artifact
        # store — split so a store miss can never masquerade as (or
        # hide) a real recompile regression in cmd-5 stats / perfproxy
        self.batches = 0
        self.requests = 0
        self.rows = 0
        self.padded_rows = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def as_dict(self):
        return {
            "compiles": self.compiles,
            "store_loads": self.store_loads,
            "batches": self.batches,
            "requests": self.requests,
            "rows": self.rows,
            "padded_rows": self.padded_rows,
            "total_ms": round(self.total_ms, 3),
            "avg_ms": round(self.total_ms / self.batches, 3)
                      if self.batches else 0.0,
            "max_ms": round(self.max_ms, 3),
        }


class _Breaker:
    """Per-(bucket, signature) circuit breaker. All methods are called
    under the engine lock.

    closed --N consecutive failures--> open --cooldown--> half_open
      ^                                 ^                    |
      +------- probe succeeds ----------+--- probe fails ----+
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    __slots__ = ("threshold", "cooldown", "state", "failures", "opened_at",
                 "trips", "shed")

    def __init__(self, threshold, cooldown):
        self.threshold = threshold
        self.cooldown = cooldown
        self.state = self.CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.trips = 0
        self.shed = 0

    def allow(self, now):
        """May a group for this bucket dispatch now? OPEN past its
        cooldown admits exactly one probe (HALF_OPEN); a second group
        while the probe is in flight is shed."""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN and now - self.opened_at >= self.cooldown:
            self.state = self.HALF_OPEN
            return True
        return False

    # tpu-resource: releases=breaker
    def record_success(self):
        self.failures = 0
        self.state = self.CLOSED

    # tpu-resource: acquires=breaker
    def record_failure(self, now):
        self.failures += 1
        if self.threshold <= 0:
            return  # breaker disabled: count but never trip
        if self.state == self.HALF_OPEN or self.failures >= self.threshold:
            self.state = self.OPEN
            self.opened_at = now
            self.trips += 1

    def as_dict(self):
        return {"state": self.state, "consecutive_failures": self.failures,
                "trips": self.trips, "shed": self.shed}


# tpu-resource: releases=flight_lock
def _publish_in_background(store, key, lock, blob):
    """Publish off the hot path: the requester already has its
    program and the bytes are already serialized — only the store
    I/O runs on a daemon thread, so no request waits on disk. The
    single-flight lock is held until the publish lands (released
    in all cases — a crashed publisher's lock is reclaimed by
    peers via the staleness takeover)."""
    def work():
        try:
            store.put(key, blob)
        finally:
            store.release(lock)

    threading.Thread(target=work, name="artifact-publish",
                     daemon=True).start()


def store_backed_compile(store, key, inline_fn, export_and_run,
                         run_from_payload, warming=False,
                         warmup_wait_s=120.0):
    """The ONE store-consult-or-compile flow, shared by the batching
    engine's :class:`AotLayerRunner` and the decode engine's program
    cache (inference/decode.py). Returns ``(run, source)`` where
    ``source`` is ``"store"`` (deserialized from the artifact store)
    or ``"inline"`` (compiled in this process).

    Caller-supplied callbacks own the program specifics:

    - ``inline_fn() -> run``: plain lower+compile (also the degrade
      path for every store failure mode);
    - ``export_and_run() -> (blob, run)``: ONE export (trace +
      StableHLO lower) serving both the published artifact and this
      process's own program — the fleet is byte-identical by
      construction, and the winner never traces twice;
    - ``run_from_payload(payload) -> run or None``: materialize a
      verified store payload (deserialize under THIS runtime, aval
      check, probe execute), quarantining + returning None when
      anything about it is off.

    ``warming``: warmup is where single-flight matters — N replicas
    warming the same key block briefly on one O_EXCL lock so exactly
    one pays the compile and the rest load its published artifact.
    The hot path never blocks on a peer: a cold key under live
    traffic compiles inline immediately (publishing in the background
    when it holds the lock)."""
    if store is None:
        return inline_fn(), "inline"
    lock = None
    if warming:
        # ONE counted lookup: acquire_or_wait reads the store itself
        # (a warm uncontended key resolves on the first acquire+read)
        # — a separate get() first would count every peer-published
        # key as a miss AND a hit, pinning the hit-ratio of a
        # perfectly warm store at 50%
        lock, payload = store.acquire_or_wait(key, timeout=warmup_wait_s)
    else:
        payload = store.get(key)
    if payload is not None:
        run = run_from_payload(payload)
        if run is not None:
            return run, "store"
        # the artifact was bad (now quarantined): try to claim the
        # compile so a good one replaces it
        lock = lock or store.try_acquire(key)
    elif not warming:
        lock = store.try_acquire(key)
    if lock is not None:
        # we own the fleet-wide compile for this key
        try:
            blob, run = export_and_run()
        except Exception:  # noqa: BLE001 - degrade to plain inline
            # export or probe failed (not every program exports):
            # free the peers NOW (they compile themselves instead of
            # waiting out the staleness horizon on a corpse), then
            # serve through the store-less path
            store.release(lock)
            return inline_fn(), "inline"
        if warming:
            # synchronous publish: peers blocked in acquire_or_wait
            # are waiting for exactly this artifact
            try:
                store.put(key, blob)
            finally:
                store.release(lock)
        else:
            _publish_in_background(store, key, lock, blob)
        return run, "inline"
    return inline_fn(), "inline"


class AotLayerRunner:
    """Execute batches for a jit-loaded :class:`TranslatedLayer` through
    per-bucket ahead-of-time compiled programs.

    The layer's exported StableHLO must be batch-polymorphic in dim 0 of
    every input (``jit.save`` with ``InputSpec([None, ...])``); each
    bucket is then lowered+compiled exactly once with the weights passed
    as runtime arguments (shared on device across buckets, never baked
    into the program) and the batch buffers donated.
    """

    def __init__(self, layer, donate=True, store=None, mesh=None):
        import jax

        self._jax = jax
        self._layer = layer
        self._donate = donate
        # serving mesh (inference/sharding.py): "single" runs the
        # pre-sharding path byte-for-byte; a sharded mesh commits the
        # resident weights to the device mesh ONCE here and every
        # bucket program compiles with those shardings as in_shardings
        # (weights stay runtime args shared across buckets). The
        # canonical descriptor rides in every ArtifactKey: a sharded
        # export can never satisfy a single-chip key or vice versa.
        self._mesh = _sharding.resolve(mesh)
        self.mesh_desc = self._mesh.descriptor
        self._sharded_state = None
        if not self._mesh.is_single:
            self._mesh.build()  # fail fast: not enough devices = here,
            # with the remedy named, never mid-request
        # persistent compiled-artifact store (serialize.artifact_store):
        # warmup and cold buckets consult it before compiling, and
        # inline compiles publish back so the NEXT process (a fresh
        # replica, a hot reload, a restart) pays zero cold compiles.
        # None + no env opt-in = store-less, the pre-store behaviour.
        self._store = store if store is not None \
            else _artifacts.default_store()
        self._fingerprint = getattr(layer, "_model_fingerprint", None)
        # serving quant mode the layer was jit-saved under (None = f32):
        # rides in every ArtifactKey (quantized programs are distinct
        # store identities), every ledger event, and the engine's
        # compile metrics — a mixed-precision fleet stays observable
        self.quant_mode = getattr(layer, "_quant_mode", None)
        self._warmup_wait_s = _env_float(
            "PADDLE_TPU_ARTIFACT_WARMUP_WAIT_S", 120.0)
        specs = getattr(layer, "_input_specs", None) or []
        if not specs:
            raise ValueError("layer has no input specs; was it jit-saved?")
        if not getattr(layer, "_polymorphic", False):
            raise ValueError(
                "dynamic batching needs a batch-polymorphic saved model: "
                "re-save with paddle.jit.save(..., input_spec="
                "[InputSpec([None, ...], dtype)]) so dim 0 exports as a "
                "symbolic size (BatchingEngine.for_callable is the "
                "fallback for fixed-shape models)")
        if not self._mesh.is_single:
            # shard once at load: these placed arrays are the runtime
            # args EVERY bucket program shares — per-device residency
            # is what makes a bigger-than-one-chip model servable
            params, p_sh = self._mesh.shard_arrays(
                [p._value for p in layer._parameters.values()])
            buffers, b_sh = self._mesh.shard_arrays(
                [jax.numpy.asarray(b)
                 for b in layer._loaded_buffers.values()])
            self._sharded_state = (params, p_sh, buffers, b_sh)
        self._trailing = []
        self._dtypes = []
        for shape, dtype in specs:
            if shape and shape[0] is not None:
                raise ValueError(
                    f"input spec {shape} has a concrete dim 0; every "
                    "input must be batch-polymorphic for bucket batching")
            if any(d is None for d in shape[1:]):
                raise ValueError(
                    f"input spec {shape} has a symbolic non-batch dim; "
                    "the batching engine buckets dim 0 only — re-save "
                    "with concrete trailing dims (or pad/bucket those "
                    "dims client-side before submitting)")
            self._trailing.append(tuple(int(d) for d in shape[1:]))
            self._dtypes.append(np.dtype(dtype))

    def default_signature(self):
        """The saved model's batch signature (for warmup)."""
        return tuple((dt.str, tr)
                     for dt, tr in zip(self._dtypes, self._trailing))

    # ------------------------------------------------- artifact store
    def _active_store(self):
        """The store to consult, or None. Needs a model fingerprint
        (jit.load computes one from the module bytes) and survives the
        operator kill switch (PADDLE_TPU_ARTIFACT_DISABLE wins even
        over an explicitly-passed store)."""
        if self._store is None or self._fingerprint is None:
            return None
        if _artifacts.disabled():
            return None
        return self._store

    def _artifact_key(self, bucket, sig):
        return _artifacts.ArtifactKey(self._fingerprint, bucket, sig,
                                      mesh=self.mesh_desc,
                                      quant=self.quant_mode)

    def _bucket_state(self, bucket, sig):
        """(flat_fn, param_arrays, buffer_arrays, specs, donate) for one
        bucket — shared by the inline compile and the export publish so
        the two can never drift (the published artifact IS the program
        the inline path would have compiled). Under a sharded mesh the
        param/buffer arrays are the mesh-committed residents and every
        spec carries its sharding, so the lowered program IS the
        sharded pjit program."""
        jax = self._jax
        layer = self._layer

        def flat_fn(param_list, buffer_list, *inputs):
            out = layer._call_fn(param_list, buffer_list, *inputs)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        if self._sharded_state is not None:
            param_arrays, p_sh, buffer_arrays, b_sh = self._sharded_state
            repl = self._mesh.replicated()
            param_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                sharding=s)
                           for a, s in zip(param_arrays, p_sh)]
            buffer_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                 sharding=s)
                            for a, s in zip(buffer_arrays, b_sh)]
            in_specs = [jax.ShapeDtypeStruct((bucket,) + tr,
                                             np.dtype(dt), sharding=repl)
                        for dt, tr in sig]
        else:
            param_arrays = [p._value for p in layer._parameters.values()]
            buffer_arrays = [jax.numpy.asarray(b)
                             for b in layer._loaded_buffers.values()]
            param_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                           for a in param_arrays]
            buffer_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                            for a in buffer_arrays]
            in_specs = [jax.ShapeDtypeStruct((bucket,) + tr, np.dtype(dt))
                        for dt, tr in sig]
        donate = tuple(range(2, 2 + len(sig))) if self._donate else ()
        return (flat_fn, param_arrays, buffer_arrays,
                (param_specs, buffer_specs, in_specs), donate)

    def _jit(self, flat_fn, donate, n_inputs):
        """The one jit construction both the inline compile and the
        export share. Single mesh: byte-for-byte the historical call
        (no sharding kwargs — the committed perfproxy baseline pins
        its fingerprints). Sharded: weights pinned to their discipline
        layout, batch inputs and outputs replicated, so the host-side
        engine (and the wire) see exactly the single-chip shapes."""
        jax = self._jax
        if self._sharded_state is None:
            return jax.jit(flat_fn, donate_argnums=donate)
        _, p_sh, _, b_sh = self._sharded_state
        repl = self._mesh.replicated()
        return jax.jit(flat_fn, donate_argnums=donate,
                       in_shardings=(list(p_sh), list(b_sh),
                                     *([repl] * n_inputs)),
                       out_shardings=repl)

    def compile(self, bucket, sig, warming=False):
        """-> (run, source): the bucket's program, loaded from the
        artifact store (``source == "store"``) or compiled inline
        (``"inline"``). Every store failure mode — miss, corrupt,
        version skew, undeserializable, probe crash — degrades to the
        inline path; a store can make this slower than compiling only
        by the cost of one verified read.

        ``warming``: warmup is where single-flight matters — N replicas
        warming the same bucket ladder block briefly on one O_EXCL
        lock so exactly one pays the compile and the rest load its
        published artifact. The hot path never blocks on a peer: a
        cold bucket under live traffic compiles inline immediately
        (publishing in the background when it holds the lock)."""
        store = self._active_store()
        if store is None:
            return self._compile_inline(bucket, sig), "inline"
        key = self._artifact_key(bucket, sig)

        def export_and_run():
            # timed end to end (export trace/lower + probe compile):
            # this event is a real cold compile and must be comparable
            # to the store-less path's aot events. One _bucket_state
            # serves both steps — rebuilding it means re-wrapping
            # every param/buffer per cold bucket.
            t0 = time.monotonic()
            state = self._bucket_state(bucket, sig)
            exported = self._export(bucket, sig, state=state)
            blob = serialize_exported(exported)
            run = self._make_run(exported, bucket, sig, state=state)
            LEDGER.record(f"serving/bucket{bucket}",
                          duration_s=time.monotonic() - t0,
                          kind="aot",
                          extra={"bucket": bucket, "via": "export",
                                 "signature": [[dt, list(tr)]
                                               for dt, tr in sig],
                                 **self._quant_extra()})
            return blob, run

        return store_backed_compile(
            store, key,
            inline_fn=lambda: self._compile_inline(bucket, sig),
            export_and_run=export_and_run,
            run_from_payload=lambda payload: self._run_from_payload(
                store, key, payload, bucket, sig),
            warming=warming, warmup_wait_s=self._warmup_wait_s)

    def _make_run(self, exported, bucket, sig, state=None):
        """run callable over an exported module, gated by everything
        bytes alone cannot prove: its input avals match the params/
        buffers/bucket we will call it with, and a zero-batch probe
        executes (paying the XLA compile HERE, never on live traffic).
        Raises on any mismatch/failure — callers decide between
        quarantine (store loads) and inline fallback (own exports)."""
        (_, param_arrays, buffer_arrays,
         (param_specs, buffer_specs, in_specs), _) = \
            state if state is not None else self._bucket_state(bucket, sig)
        # mesh skew is a clean KEY miss in the normal flow; this gate
        # is the defense in depth (copied store dir, hand-loaded blob):
        # a program exported for N devices must never reach an engine
        # whose mesh expects M
        _sharding.check_nr_devices(
            exported, None if self._sharded_state is None else self._mesh)
        # canonicalize through jax's dtype rules (x64 disabled traces
        # i64/f64 specs as i32/f32): the EXPORTED avals are always
        # canonical, and the inline path canonicalizes identically at
        # lowering — the two must be compared in the same space
        canon = self._jax.dtypes.canonicalize_dtype
        expect = [(tuple(s.shape), np.dtype(canon(s.dtype)))
                  for s in (*param_specs, *buffer_specs, *in_specs)]
        got = [(tuple(a.shape), np.dtype(a.dtype))
               for a in exported.in_avals]
        if got != expect:
            raise ValueError(
                f"aval mismatch: artifact {got} vs expected {expect}")

        def run(batch_arrays):
            out = exported.call(param_arrays, buffer_arrays, *batch_arrays)
            return [np.asarray(o) for o in out]

        probe = [np.zeros((bucket,) + tuple(tr), np.dtype(dt))
                 for dt, tr in sig]
        outs = run(probe)
        for o in outs:
            if getattr(o, "ndim", 0) == 0 or o.shape[0] != bucket:
                raise ValueError(
                    f"probe output shape {getattr(o, 'shape', ())} "
                    f"does not keep the {bucket}-row batch dim")
        return run

    def _run_from_payload(self, store, key, payload, bucket, sig):
        """Materialize a store artifact into a run callable, or None
        (with the artifact quarantined) when anything about it is off.
        The payload already passed sha256 verification; _make_run
        checks the rest (deserializes under THIS runtime, aval match,
        probe execution) — so a store-loaded program can never first
        fail on live traffic."""
        t0 = time.monotonic()
        try:
            exported = deserialize_exported(payload)
            run = self._make_run(exported, bucket, sig)
        except Exception as e:  # noqa: BLE001 - any bad artifact degrades
            store.quarantine(key, str(e))
            return None
        # the ledger distinguishes store loads from real compiles, so
        # single-flight across processes is assertable ("exactly one
        # kind=aot event per bucket, fleet-wide") and perfproxy's
        # compile counts never conflate a store miss with a regression
        LEDGER.record(f"serving/bucket{bucket}",
                      duration_s=time.monotonic() - t0, kind="store",
                      extra={"bucket": bucket,
                             "artifact": key.digest(),
                             "signature": [[dt, list(tr)]
                                           for dt, tr in sig],
                             **self._quant_extra()})
        return run

    def _export(self, bucket, sig, state=None):
        """Export this bucket's program (the same flat_fn + specs +
        donation the inline compile uses) — ONE trace + lower that the
        publish path serializes and the winner's own run is built on."""
        from jax import export as jax_export

        flat_fn, _, _, (param_specs, buffer_specs, in_specs), donate = \
            state if state is not None else self._bucket_state(bucket, sig)
        with warnings.catch_warnings():
            # same carve-out as the inline compile: unused donations on
            # tiny models are an optimization miss, not noise-worthy
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return jax_export.export(
                self._jit(flat_fn, donate, len(in_specs)))(
                    param_specs, buffer_specs, *in_specs)

    def _export_bytes(self, bucket, sig):
        """Serialized form of :meth:`_export` (the published payload)."""
        return serialize_exported(self._export(bucket, sig))

    def _quant_extra(self):
        """Ledger-event mode/mesh tags. Empty for f32/single, so every
        historical event shape (and the committed perfproxy baseline's
        f32 single-chip sections) stays byte-identical."""
        extra = {}
        if self.quant_mode:
            extra["quant"] = self.quant_mode
        if self.mesh_desc != _sharding.SINGLE:
            extra["mesh"] = self.mesh_desc
        return extra

    def store_stats(self):
        store = self._active_store()
        return store.stats() if store is not None else None

    # ---------------------------------------------------- inline compile
    def _compile_inline(self, bucket, sig):
        """Lower + compile the bucket's program. Called once per bucket
        by the engine's cache; the compiled callable takes the padded
        numpy batch arrays and returns a list of numpy outputs."""
        (flat_fn, param_arrays, buffer_arrays,
         (param_specs, buffer_specs, in_specs), donate) = \
            self._bucket_state(bucket, sig)
        t0 = time.monotonic()
        with warnings.catch_warnings():
            # tiny models may leave a donated batch buffer unused; that
            # is an optimization miss, not an error worth a warning per
            # compile
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = (self._jit(flat_fn, donate, len(in_specs))
                        .lower(param_specs, buffer_specs, *in_specs)
                        .compile())
        # every AOT compile lands in the process compile ledger: bucket,
        # duration, cost_analysis FLOPs/bytes, structural HLO
        # fingerprint — what bench.py perfproxy diffs against its
        # committed baseline
        LEDGER.record(f"serving/bucket{bucket}",
                      duration_s=time.monotonic() - t0, compiled=compiled,
                      kind="aot",
                      extra={"bucket": bucket,
                             "signature": [[dt, list(tr)]
                                           for dt, tr in sig],
                             **self._quant_extra()})

        def run(batch_arrays):
            out = compiled(param_arrays, buffer_arrays, *batch_arrays)
            # np.asarray is the device->host readback: the true sync
            # point (PERF.md), and the bytes the server will encode
            return [np.asarray(o) for o in out]

        return run

    def prime(self, run, bucket, sig):
        """No-op: compile() above already AOT-compiled the program."""


class CallableRunner:
    """Fallback runner wrapping any ``fn(*arrays) -> list[array]`` (e.g.
    a fixed-shape model or a plain python function). There is no AOT
    cache to manage — the bucket's real compile happens inside XLA's
    own jit cache on the first batch executed at that size, so
    ``warmup`` primes each bucket by running a zero batch through it."""

    def __init__(self, fn):
        self._fn = fn

    def default_signature(self):
        return None

    def compile(self, bucket, sig, warming=False):
        fn = self._fn

        def run(batch_arrays):
            out = fn(*batch_arrays)
            if not isinstance(out, (list, tuple)):
                out = [out]
            return [np.asarray(o._value if hasattr(o, "_value") else o)
                    for o in out]

        return run, "inline"

    def store_stats(self):
        return None

    def prime(self, run, bucket, sig):
        """Execute a zero batch so XLA traces+compiles this bucket now,
        not on the first real request."""
        run([np.zeros((bucket,) + tuple(tr), np.dtype(dt))
             for dt, tr in sig])


class BatchingEngine:
    """Shared dynamic-batching front end for a served model.

    ``infer(inputs)`` blocks the calling thread until its rows come back
    from a coalesced batch; any number of threads (server handlers,
    cloned predictors) may call it concurrently. Construction::

        engine = BatchingEngine.for_layer(layer, max_batch_size=32,
                                          max_wait_ms=2.0, max_queue=256)
        engine.warmup()            # precompile all power-of-2 buckets
        outs = engine.infer([x])   # x: [rows, ...]; rows <= max splits

    Knobs:
      max_batch_size  cap on coalesced rows per fired batch (the
                      Config.enable_tensorrt_engine(max_batch_size=...)
                      knob routes here on TPU)
      max_wait_ms     scheduler fires a partial batch once the oldest
                      pending request has waited this long
      max_queue       bounded pending-request cap; beyond it submit()
                      sheds with EngineOverloaded (wire status 2)
      breaker_threshold / breaker_cooldown
                      poisoned-bucket quarantine (see _Breaker); env
                      defaults PADDLE_TPU_SERVING_BREAKER_*
      watchdog_interval / wedge_timeout
                      scheduler self-healing cadence; env defaults
                      PADDLE_TPU_SERVING_WATCHDOG_INTERVAL / _WEDGE_TIMEOUT
    """

    def __init__(self, runner, max_batch_size=32, max_wait_ms=2.0,
                 max_queue=256, name="engine", breaker_threshold=None,
                 breaker_cooldown=None, watchdog_interval=None,
                 wedge_timeout=None, cold_compile_timeout=None):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._runner = runner
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.name = name
        self.breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else _env_int("PADDLE_TPU_SERVING_BREAKER_THRESHOLD", 3))
        self.breaker_cooldown = float(
            breaker_cooldown if breaker_cooldown is not None
            else _env_float("PADDLE_TPU_SERVING_BREAKER_COOLDOWN", 5.0))
        self.watchdog_interval = float(
            watchdog_interval if watchdog_interval is not None
            else _env_float("PADDLE_TPU_SERVING_WATCHDOG_INTERVAL", 0.5))
        self.wedge_timeout = float(
            wedge_timeout if wedge_timeout is not None
            else _env_float("PADDLE_TPU_SERVING_WEDGE_TIMEOUT", 30.0))
        # a cold-bucket compile runs on its own thread, outside the
        # scheduler the watchdog heartbeats — bound it separately
        # (generous: XLA compiles legitimately take tens of seconds)
        # so a wedged compile fails its waiters retryably instead of
        # hanging them forever. Enforced by the watchdog; 0 disables.
        self.cold_compile_timeout = float(
            cold_compile_timeout if cold_compile_timeout is not None
            else _env_float("PADDLE_TPU_SERVING_COLD_COMPILE_TIMEOUT",
                            300.0))
        # old duck-typed runners (pre-artifact-store protocol) define
        # compile(bucket, sig) -> run; the current protocol is
        # compile(bucket, sig, warming=False) -> (run, source). Detect
        # once here so both keep working — the same tolerance health()
        # extends to runners without store_stats()
        try:
            import inspect

            ps = inspect.signature(runner.compile).parameters
            self._compile_takes_warming = (
                "warming" in ps
                or any(p.kind is p.VAR_KEYWORD for p in ps.values()))
        except (TypeError, ValueError):
            self._compile_takes_warming = True
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = []  # FIFO of _Request
        self._cache = {}  # (bucket, sig) -> compiled run callable
        self._compiling = {}  # (bucket, sig) -> Event for in-flight compile
        self._bucket_stats = {}  # (bucket, sig) -> _BucketStats
        self._breakers = {}  # (bucket, sig) -> _Breaker
        self._deadline_seen = False  # any deadline-bearing submit yet?
        self._init_metrics()
        self._declared = []  # bucket row counts from warmup()
        self._cold_threads = []  # in-flight cold-bucket compile threads
        self._cold_seq = 0
        self._cold_inflight = {}  # token -> (group, t_start): groups in
        # cold-compile threads, invisible to the scheduler heartbeat —
        # the watchdog bounds these by cold_compile_timeout
        self._closed = False
        self._closed_ev = threading.Event()
        # --- scheduler self-healing state ---
        # generation token: a watchdog restart bumps it; a superseded
        # scheduler thread notices and exits instead of double-serving
        self._sched_gen = 0
        self._heartbeat = time.monotonic()  # bumped each scheduler loop
        self._inflight = {}  # gen -> group popped but not yet delivered
        self._watchdog = None  # before the scheduler starts: its crash
        self._scheduler = threading.Thread(  # handler reads it
            target=self._run_scheduler, args=(0,),
            name=f"{name}-scheduler", daemon=True)
        self._scheduler.start()
        if self.watchdog_interval > 0:
            self._watchdog = threading.Thread(target=self._run_watchdog,
                                              name=f"{name}-watchdog",
                                              daemon=True)
            self._watchdog.start()

    # -------------------------------------------------------- telemetry
    def _init_metrics(self):
        """Per-engine obs instruments (obs.metrics). These ARE the
        engine's counters — cmd-5 ``stats`` and cmd-3 ``health`` read
        them (under the engine lock, so a snapshot is never torn) and
        the process registry exposes them to Prometheus through a
        registered collector. Instruments are engine-owned (const label
        ``engine=<name>``) rather than global so every engine instance
        keeps an isolated view; the exposition merges same-name
        families across engines."""
        cl = {"engine": self.name}
        M = obs_metrics
        lat_buckets = M.log_buckets(0.0001, 4.0, 10)
        self._m_requests = M.Counter(
            "paddle_serving_requests_total",
            "Requests admitted to the batching engine", const_labels=cl)
        self._m_rows = M.Counter(
            "paddle_serving_rows_total",
            "Input rows admitted to the batching engine", const_labels=cl)
        self._m_shed = M.Counter(
            "paddle_serving_shed_total",
            "Requests shed (reason: queue_full | quarantine)",
            labelnames=("reason",), const_labels=cl)
        self._m_deadline = M.Counter(
            "paddle_serving_deadline_total",
            "Deadline outcomes (stage: expired = dropped pre-dispatch, "
            "zero compute; late = expired in flight, compute spent)",
            labelnames=("stage",), const_labels=cl)
        self._m_restarts = M.Counter(
            "paddle_serving_scheduler_restarts_total",
            "Watchdog scheduler restarts", const_labels=cl)
        # quant and mesh ride as const labels (properties of the served
        # model/engine, not of an individual compile): a mixed
        # precision-and-topology fleet shows per-mode, per-mesh
        # compile/store-load series on one dashboard
        quant = getattr(self._runner, "quant_mode", None) or "f32"
        mesh = getattr(self._runner, "mesh_desc", None) or _sharding.SINGLE
        self._m_compiles = M.Counter(
            "paddle_serving_compiles_total",
            "Bucket program materializations (source: inline = a real "
            "XLA compile; store = deserialized from the persistent "
            "artifact store; quant: the serving quantization mode; "
            "mesh: the serving mesh descriptor)",
            labelnames=("bucket", "source"),
            const_labels={**cl, "quant": quant, "mesh": mesh})
        self._m_batches = M.Counter(
            "paddle_serving_batches_total",
            "Batches executed", labelnames=("bucket",), const_labels=cl)
        self._m_batch_rows = M.Counter(
            "paddle_serving_batch_rows_total",
            "Real rows executed per bucket", labelnames=("bucket",),
            const_labels=cl)
        self._m_padded = M.Counter(
            "paddle_serving_padded_rows_total",
            "Padding rows executed per bucket", labelnames=("bucket",),
            const_labels=cl)
        self._m_queue_depth = M.Gauge(
            "paddle_serving_queue_depth",
            "Pending requests in the bounded queue", const_labels=cl)
        self._m_queue_wait = M.Histogram(
            "paddle_serving_queue_wait_seconds",
            "Enqueue-to-dispatch wait per request",
            const_labels=cl, buckets=lat_buckets)
        self._m_occupancy = M.Histogram(
            "paddle_serving_batch_occupancy",
            "Real rows / bucket size per executed batch",
            const_labels=cl,
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self._m_exec = M.Histogram(
            "paddle_serving_batch_exec_seconds",
            "Batch execute duration", labelnames=("bucket",),
            const_labels=cl, buckets=lat_buckets)
        self._instruments = [
            self._m_requests, self._m_rows, self._m_shed,
            self._m_deadline, self._m_restarts, self._m_compiles,
            self._m_batches, self._m_batch_rows, self._m_padded,
            self._m_queue_depth, self._m_queue_wait, self._m_occupancy,
            self._m_exec]
        # weakref so a leaked (never-closed) engine can still be
        # garbage-collected; a dead ref returns None, which the
        # registry treats as "auto-unregister me"
        ref = weakref.ref(self)

        def _collector():
            eng = ref()
            return eng._collect_families() if eng is not None else None

        self._obs_collector = _collector
        obs_metrics.REGISTRY.register_collector(_collector)

    def _collect_families(self):
        # one engine-lock acquisition for the whole family set: the
        # exposition sees the same consistent view cmd-5 stats does
        with self._lock:
            self._m_queue_depth.set(len(self._pending))
            return [m.collect() for m in self._instruments]

    # ------------------------------------------------------- constructors
    @classmethod
    def for_layer(cls, layer, donate=True, artifact_store=None,
                  mesh=None, **kw):
        """Engine over a jit-loaded batch-polymorphic TranslatedLayer
        (per-bucket AOT compile, donation on the batch buffers).
        ``artifact_store``: a serialize.ArtifactStore for persistent
        cross-process program reuse (default: env-gated
        ``default_store()`` — PADDLE_TPU_ARTIFACT_DIR opts in).
        ``mesh``: a serving mesh descriptor (``"tp2"``,
        ``"fsdp2xtp2"``; default env ``PADDLE_TPU_SERVING_MESH``, else
        single-chip) — weights shard once at load and every bucket
        program becomes a per-(bucket, mesh) pjit program (README
        "Sharded serving")."""
        return cls(AotLayerRunner(layer, donate=donate,
                                  store=artifact_store, mesh=mesh), **kw)

    @classmethod
    def for_callable(cls, fn, **kw):
        """Engine over any ``fn(*arrays) -> outputs`` callable."""
        return cls(CallableRunner(fn), **kw)

    # ------------------------------------------------------------- submit
    def infer(self, inputs, timeout=None, deadline=None, trace_id=None):
        """Run one request (list of arrays sharing dim 0 = rows) through
        the engine; returns the list of output arrays for those rows.

        ``timeout`` bounds only this caller's wait; ``deadline`` (an
        absolute ``time.monotonic()`` point) is additionally honored by
        the scheduler: an expired request is purged before dispatch
        (DeadlineExceeded) and a group never waits past the tightest
        deadline of its members.

        ``trace_id`` (default: the thread's ambient obs.tracing id)
        tags the request's recorded spans — ``serving.request`` (this
        whole call), ``serving.queue`` (enqueue -> dispatch) and
        ``serving.execute`` (its batch) — so a wire-propagated id can
        be followed across threads.

        Requests larger than max_batch_size are split into chunks and
        re-joined (the split path); each chunk occupies its own queue
        slot so an oversized request cannot bypass the shed cap.
        """
        inputs = [np.ascontiguousarray(a) for a in inputs]
        if not inputs:
            raise ValueError("infer() needs at least one input array")
        rows = int(inputs[0].shape[0]) if inputs[0].ndim else 0
        if rows <= 0:
            raise ValueError("inputs must have a leading batch dim >= 1")
        for a in inputs:
            if a.ndim == 0 or a.shape[0] != rows:
                raise ValueError(
                    "all inputs of one request must share dim 0 "
                    f"(got {[tuple(x.shape) for x in inputs]})")
        if trace_id is None:
            trace_id = obs_tracing.current_trace_id()
        t0 = time.perf_counter()
        try:
            if deadline is not None and time.monotonic() >= deadline:
                self._m_deadline.inc(stage="expired")
                raise DeadlineExceeded(
                    f"{self.name}: deadline passed before submission")
            if rows > self.max_batch_size:
                out = self._infer_split(inputs, rows, timeout, deadline,
                                        trace_id)
            else:
                req = self._submit(inputs, rows, deadline, trace_id)
                out = self._wait(req, timeout)
        except BaseException as e:
            self._span_request(trace_id, t0, rows, type(e).__name__)
            raise
        self._span_request(trace_id, t0, rows, "ok")
        return out

    def _span_request(self, trace_id, t0, rows, outcome):
        """End-of-request telemetry: aggregate always; a full span
        record only for traced requests (the bounded span buffer is a
        debugging surface, not a per-request firehose)."""
        dt = time.perf_counter() - t0
        if trace_id is not None:
            obs_tracing.record_span("serving.request", dt,
                                    trace_id=trace_id, engine=self.name,
                                    rows=rows, outcome=outcome)
        else:
            obs_tracing.observe("serving.request", dt)

    def _infer_split(self, inputs, rows, timeout, deadline, trace_id):
        n_chunks = -(-rows // self.max_batch_size)
        if n_chunks > self.max_queue:
            # a deterministic can-never-fit request must get a permanent
            # error, not EngineOverloaded: status 2 tells clients to back
            # off and RETRY, and this retry can never succeed
            raise ValueError(
                f"request of {rows} rows needs {n_chunks} chunks of "
                f"max_batch_size={self.max_batch_size} but the queue cap "
                f"is {self.max_queue}: split the request client-side or "
                "raise max_queue/max_batch_size")
        chunks = []
        for lo in range(0, rows, self.max_batch_size):
            hi = min(rows, lo + self.max_batch_size)
            chunks.append([a[lo:hi] for a in inputs])
        # all chunks are enqueued atomically: a partially-admitted
        # oversized request would compute rows only to discard them
        # when a later chunk sheds. One shared deadline covers them all
        # (the tightest deadline in any group a chunk joins).
        reqs = self._submit_chunks(
            chunks, min_bucket=min(2, self.max_batch_size),
            deadline=deadline, trace_id=trace_id)
        wait_until = (None if timeout is None
                      else time.monotonic() + timeout)
        parts = []
        for i, r in enumerate(reqs):
            left = (None if wait_until is None
                    else max(0.0, wait_until - time.monotonic()))
            try:
                parts.append(self._wait(r, left))
            except BaseException as e:
                # the joined result can never be produced now: pull the
                # sibling chunks still queued (freeing their shed-cap
                # slots) and fail the rest, or they fire full padded
                # batches nobody will ever read
                with self._cond:
                    for rest in reqs[i + 1:]:
                        try:
                            self._pending.remove(rest)
                        except ValueError:
                            pass  # already grouped/in flight; discarded
                for rest in reqs[i + 1:]:
                    rest.fail(e)
                raise
        return [np.concatenate([p[i] for p in parts])
                for i in range(len(parts[0]))]

    def _submit(self, inputs, rows, deadline=None, trace_id=None):
        return self._submit_chunks([inputs], deadline=deadline,
                                   trace_id=trace_id)[0]

    def _submit_chunks(self, chunks, min_bucket=1, deadline=None,
                       trace_id=None):
        """Admit every chunk or none (one queue slot per chunk, so an
        oversized request still counts fully against the shed cap)."""
        chaos.hit("serving.submit")
        with self._cond:
            if self._closed:
                raise EngineClosed(f"{self.name} is closed")
            if len(self._pending) + len(chunks) > self.max_queue:
                self._m_shed.inc(reason="queue_full")
                raise EngineOverloaded(
                    f"{self.name} queue full ({len(self._pending)} pending,"
                    f" cap {self.max_queue}, need {len(chunks)} slots); "
                    "request shed")
            reqs = []
            if deadline is not None:
                self._deadline_seen = True
            for chunk in chunks:
                rows = int(chunk[0].shape[0])
                req = _Request(chunk, rows, _signature(chunk), min_bucket,
                               deadline, trace_id)
                self._pending.append(req)
                self._m_requests.inc()
                self._m_rows.inc(rows)
                reqs.append(req)
            self._cond.notify_all()
        return reqs

    def _wait(self, req, timeout):
        if req.deadline is not None:
            # the scheduler purges expired requests (DeadlineExceeded);
            # the small grace lets that cleaner error win over a bare
            # TimeoutError when both fire together
            dl_left = max(0.0, req.deadline - time.monotonic()) + 0.25
            timeout = dl_left if timeout is None else min(timeout, dl_left)
        if not req.event.wait(timeout):
            # abandon: pull it out of the queue so the scheduler never
            # spends a batch slot computing rows nobody will read
            with self._cond:
                try:
                    self._pending.remove(req)
                except ValueError:
                    pass  # already grouped/in flight; result is discarded
            if (req.deadline is not None
                    and time.monotonic() >= req.deadline):
                # separate counter: deadline_expired promises "dropped
                # BEFORE dispatch, no compute spent" — an in-flight
                # expiry may have burned a full batch, and lumping it
                # in would skew the metric operators size budgets by
                self._m_deadline.inc(stage="late")
                raise DeadlineExceeded(
                    f"{self.name}: deadline passed while the request was "
                    "in flight; the result (if any) was discarded")
            raise TimeoutError("engine did not answer within timeout")
        if req.error is not None:
            raise req.error
        return req.outputs

    # ---------------------------------------------------------- scheduler
    def _run_scheduler(self, gen):
        try:
            self._scheduler_loop(gen)
        except Exception:  # noqa: BLE001 - watchdog owns recovery
            # The loop itself broke (injected chaos, unexpected bug).
            # Log it — a scheduler that vanishes without a traceback is
            # undebuggable — then die WITHOUT clearing _inflight: the
            # watchdog fails that group with a retryable status and
            # starts a replacement scheduler for the parked requests.
            traceback.print_exc()
            if self._watchdog is None:
                # watchdog disabled (interval 0): nobody else will
                # recover, so self-heal inline — the crash must never
                # strand the in-flight group or the parked queue
                self._restart_scheduler(gen, "died (watchdog disabled)")

    def _scheduler_loop(self, gen):
        while True:
            # unguarded on purpose: a single f64 store is GIL-atomic, the
            # value is monotonic, and the watchdog only compares it to a
            # staleness threshold — a lock here would put one acquisition
            # on every scheduler iteration for no correctness gain
            self._heartbeat = time.monotonic()  # tpu-lint: disable=TPU305  # benign race: GIL-atomic monotonic bump
            group = self._next_group(gen)
            if group is None:
                return  # closed and drained, or superseded by a restart
            with self._lock:
                self._inflight[gen] = group
            # From here until dispatch hand-off, an unhandled exception
            # (e.g. injected chaos) kills this thread WITH the group
            # still recorded in _inflight — the watchdog then fails
            # exactly that group with a retryable status (never a hang)
            # and restarts the scheduler for the parked requests.
            chaos.hit("serving.scheduler.loop")
            bucket = self._group_bucket(group)
            key = (bucket, group[0].sig)
            now = time.monotonic()
            with self._lock:
                br = self._breaker_for(key)
                allowed = br.allow(now)
                if not allowed:
                    br.shed += len(group)
                    self._m_shed.inc(len(group), reason="quarantine")
            if not allowed:
                err = BucketQuarantined(
                    f"{self.name} bucket {bucket} is quarantined after "
                    f"{br.failures} consecutive failures; retry after "
                    f"cooldown ({self.breaker_cooldown}s)")
                for r in group:
                    r.fail(err)
                with self._lock:
                    self._inflight.pop(gen, None)
                continue
            with self._lock:
                cold = key not in self._cache
            if cold:
                # a cold bucket pays a multi-second XLA compile: run it
                # on its own thread so already-compiled buckets keep
                # flowing instead of stalling head-of-line behind it.
                # The cold thread owns delivery from here (its guarded
                # wrapper cannot strand waiters).
                with self._lock:
                    self._cold_seq += 1
                    token = self._cold_seq
                t = threading.Thread(target=self._run_cold_group,
                                     args=(token, group, br),
                                     name=f"{self.name}-cold-compile",
                                     daemon=True)
                with self._lock:
                    self._inflight.pop(gen, None)
                    self._cold_inflight[token] = (group, time.monotonic())
                    self._cold_threads = [x for x in self._cold_threads
                                          if x.is_alive()]
                    self._cold_threads.append(t)
                t.start()
            else:
                try:
                    self._run_group_guarded(group, br)
                finally:
                    # _run_group_guarded never raises (it fails the
                    # group instead), so waiters are already answered —
                    # clear even on a BaseException so a later watchdog
                    # restart cannot double-fail a delivered group
                    with self._lock:
                        self._inflight.pop(gen, None)

    def _run_cold_group(self, token, group, br):
        """Like _run_group_guarded, but the breaker outcome is recorded
        only while this group still owns its cold-inflight token: once
        the watchdog timed the group out it already recorded a failure
        for this incident — the zombie thread's eventual outcome must
        not count the same incident twice, and a late zombie success
        must not flip an OPEN breaker straight past its cooldown."""
        try:
            self._run_group(group)
        except Exception as e:  # noqa: BLE001 - fail the group only
            now = time.monotonic()
            with self._lock:
                owned = self._cold_inflight.pop(token, None) is not None
                if br is not None and owned:
                    br.record_failure(now)
            for r in group:
                r.fail(e)
        else:
            with self._lock:
                owned = self._cold_inflight.pop(token, None) is not None
                if br is not None and owned:
                    br.record_success()

    def _run_group_guarded(self, group, br=None):
        try:
            self._run_group(group)
        except Exception as e:  # noqa: BLE001 - fail the group only
            now = time.monotonic()
            with self._lock:
                if br is not None:
                    br.record_failure(now)
            for r in group:
                r.fail(e)
        else:
            with self._lock:
                if br is not None:
                    br.record_success()

    def _purge_expired_locked(self, now):
        """Drop pending requests whose deadline already passed — before
        dispatch, so no compute is spent on a client that gave up.
        Called with the lock held."""
        if not self._deadline_seen:
            # deadline-free deployments skip the per-iteration O(queue)
            # scan entirely (sticky flag: set on the first deadline-
            # bearing submit, never cleared)
            return
        expired = [r for r in self._pending
                   if r.deadline is not None and now >= r.deadline]
        if not expired:
            return
        for r in expired:
            self._pending.remove(r)
            self._m_deadline.inc(stage="expired")
        err = DeadlineExceeded(
            f"{self.name}: deadline passed while queued; request dropped "
            "before dispatch")
        for r in expired:
            r.fail(err)

    def _next_group(self, gen):
        """Block until a same-signature group is ready to fire: either
        max_batch_size rows are pending or the oldest request has waited
        max_wait_ms — or the tightest deadline in the candidate group is
        about to pass. Returns the popped group (None = engine closed or
        this scheduler generation superseded)."""
        with self._cond:
            while True:
                if self._sched_gen != gen:
                    return None  # a watchdog restart superseded us
                now = time.monotonic()
                self._purge_expired_locked(now)
                if not self._pending:
                    if self._closed:
                        return None
                    # every producer of work notifies: submit, close and
                    # restart all notify_all under this same condition —
                    # an idle scheduler parked here is woken by ANY
                    # state change it could act on
                    self._cond.wait()  # tpu-lint: disable=TPU303  # all three wake sources notify_all under _cond
                    continue
                head = self._pending[0]
                group, rows = [], 0
                for r in self._pending:
                    if r.sig != head.sig:
                        continue
                    if rows + r.rows > self.max_batch_size:
                        break
                    group.append(r)
                    rows += r.rows
                deadline = head.t_enqueue + self.max_wait_s
                tight = min((r.deadline for r in group
                             if r.deadline is not None), default=None)
                if tight is not None:
                    # never coalesce-wait past the tightest deadline of
                    # the group's members; the 5ms margin dispatches the
                    # group BEFORE that deadline (the purge above would
                    # otherwise drop the request at the exact instant
                    # its group was due to fire)
                    deadline = min(deadline, tight - 0.005)
                if (rows >= self.max_batch_size or now >= deadline
                        or self._closed):
                    t_pop = time.monotonic()
                    for r in group:
                        self._pending.remove(r)
                        wait = t_pop - r.t_enqueue
                        self._m_queue_wait.observe(wait)
                        if r.trace_id is not None:
                            obs_tracing.record_span(
                                "serving.queue", wait,
                                trace_id=r.trace_id, engine=self.name,
                                rows=r.rows)
                    return group
                self._cond.wait(deadline - now)

    def _group_bucket(self, group):
        """Bucket for a popped group: next power of two over the
        coalesced rows, floored by any chunk's min_bucket (a solo
        1-row split tail pads to bucket 2 to stay in the bitwise-stable
        batch >= 2 regime)."""
        want = max(sum(r.rows for r in group),
                   max(r.min_bucket for r in group))
        return bucket_rows(want, self.max_batch_size)

    def _run_group(self, group):
        rows = sum(r.rows for r in group)
        sig = group[0].sig
        bucket = self._group_bucket(group)
        run, _ = self._compiled(
            bucket, sig,
            trace_id=next((r.trace_id for r in group
                           if r.trace_id is not None), None))
        n_in = len(sig)
        batch = []
        for i in range(n_in):
            parts = [r.inputs[i] for r in group]
            if bucket > rows:
                pad_shape = (bucket - rows,) + parts[0].shape[1:]
                parts.append(np.zeros(pad_shape, parts[0].dtype))
            batch.append(np.concatenate(parts) if len(parts) > 1
                         else parts[0])
        chaos.hit("serving.execute")
        chaos.hit(f"serving.execute.bucket{bucket}")
        t0 = time.monotonic()
        outs = run(batch)
        dt_ms = (time.monotonic() - t0) * 1000.0
        # one execute per group; traced requests each get a span with
        # the shared duration, untraced traffic only feeds the table
        tids = {r.trace_id for r in group if r.trace_id is not None}
        if tids:
            for tid in tids:
                obs_tracing.record_span(
                    "serving.execute", dt_ms / 1000.0, trace_id=tid,
                    engine=self.name, bucket=bucket, rows=rows)
        else:
            obs_tracing.observe("serving.execute", dt_ms / 1000.0)
        for j, o in enumerate(outs):
            if getattr(o, "ndim", 0) == 0 or o.shape[0] != bucket:
                raise ValueError(
                    f"output {j} has shape {tuple(getattr(o, 'shape', ()))}"
                    f" but the batch has {bucket} rows: every output must "
                    "keep the batch dim as dim 0 so per-request rows can "
                    "be sliced back — batch-reduced outputs cannot go "
                    "through the batching engine")
        off = 0
        for r in group:
            r.outputs = [o[off:off + r.rows] for o in outs]
            off += r.rows
            r.event.set()
        with self._lock:
            st = self._stats_for(bucket, sig)
            st.batches += 1
            st.requests += len(group)
            st.rows += rows
            st.padded_rows += bucket - rows
            st.total_ms += dt_ms
            st.max_ms = max(st.max_ms, dt_ms)
            bs = str(bucket)
            self._m_batches.inc(bucket=bs)
            self._m_batch_rows.inc(rows, bucket=bs)
            self._m_padded.inc(bucket - rows, bucket=bs)
            self._m_exec.observe(dt_ms / 1000.0, bucket=bs)
            self._m_occupancy.observe(rows / bucket)

    # ----------------------------------------------------------- watchdog
    def _run_watchdog(self):
        """Restart a dead or wedged scheduler. Death (an unhandled
        exception escaped the loop — e.g. injected chaos) and wedging
        (heartbeat stale AND the oldest pending request stale, so a long
        legitimate execute with a fresh queue never false-positives) get
        the same treatment: bump the generation, fail only the in-flight
        group with a retryable status, start a fresh scheduler thread.
        Parked requests stay queued and are served by the new thread."""
        while not self._closed_ev.wait(self.watchdog_interval):
            with self._lock:
                if self._closed:
                    return
                gen = self._sched_gen
                th = self._scheduler
                hb = self._heartbeat
                head = self._pending[0] if self._pending else None
                group = self._inflight.get(gen)
            now = time.monotonic()
            dead = not th.is_alive()
            # staleness witness: the queue head, or — when the queue is
            # empty — the in-flight group itself (a scheduler wedged
            # mid-execute on the LAST request must still be caught, or
            # its waiters hang forever)
            if head is not None:
                oldest = head.t_enqueue
            elif group:
                oldest = min(r.t_enqueue for r in group)
            else:
                oldest = None
            wedged = (oldest is not None
                      and now - hb > self.wedge_timeout
                      and now - oldest > self.wedge_timeout)
            if dead:
                self._restart_scheduler(gen, "died")
            elif wedged:
                self._restart_scheduler(gen, "wedged (heartbeat stale)")
            self._fail_overdue_cold_groups(now)

    def _fail_overdue_cold_groups(self, now):
        """Cold-compile groups run outside the scheduler the heartbeat
        watches; bound them by cold_compile_timeout so a wedged XLA
        compile fails its waiters retryably instead of hanging them
        (and every later same-bucket group queued behind its in-flight
        compile event) forever. The zombie thread may still finish and
        cache its program — results go nowhere, r.fail is a no-op once
        delivery happened."""
        if self.cold_compile_timeout <= 0:
            return
        with self._lock:
            overdue = [(tok, grp)
                       for tok, (grp, t0) in self._cold_inflight.items()
                       if now - t0 > self.cold_compile_timeout]
            for tok, _ in overdue:
                self._cold_inflight.pop(tok, None)
        for _, grp in overdue:
            # count toward the bucket's breaker: a compile that keeps
            # wedging must quarantine the bucket (sheds happen BEFORE
            # cold dispatch), which bounds the stuck-thread population
            # at breaker_threshold instead of one per client retry
            key = (self._group_bucket(grp), grp[0].sig)
            with self._lock:
                self._breaker_for(key).record_failure(time.monotonic())
            err = RetryableError(
                f"{self.name}: cold bucket compile/execute exceeded "
                f"cold_compile_timeout={self.cold_compile_timeout}s; "
                "request failed retryable (the compile may still finish "
                "and cache its program for the next attempt)")
            for r in grp:
                r.fail(err)

    def _restart_scheduler(self, observed_gen, reason):
        with self._cond:
            if self._closed or observed_gen != self._sched_gen:
                return  # already restarted (or shutting down)
            self._sched_gen += 1
            gen = self._sched_gen
            stranded = self._inflight.pop(observed_gen, None)
            if stranded:
                # if the stranded group was a HALF_OPEN probe, count it
                # as a failed probe (back to OPEN, fresh cooldown) — the
                # probe's own record_failure may never run, and a
                # breaker stuck HALF_OPEN sheds its bucket forever. A
                # CLOSED breaker is left alone: a scheduler death is not
                # the bucket's fault.
                key = (self._group_bucket(stranded), stranded[0].sig)
                br = self._breakers.get(key)
                if br is not None and br.state == _Breaker.HALF_OPEN:
                    br.record_failure(time.monotonic())
            self._m_restarts.inc()
            self._heartbeat = time.monotonic()
            t = threading.Thread(target=self._run_scheduler, args=(gen,),
                                 name=f"{self.name}-scheduler-g{gen}",
                                 daemon=True)
            self._scheduler = t
            # start INSIDE the lock: a concurrent close() reading
            # self._scheduler must never join() a not-yet-started
            # thread (RuntimeError). The new thread just parks on this
            # same lock until we release it.
            t.start()  # tpu-lint: disable=TPU304  # load-bearing: close() must never join an unstarted thread
            self._cond.notify_all()  # a superseded thread parked in wait()
        if stranded:
            err = SchedulerRestarted(
                f"{self.name} scheduler {reason} and was restarted; this "
                "request's group was in flight — its results (if any) "
                "were discarded, never delivered — retry it")
            for r in stranded:
                r.fail(err)

    # ------------------------------------------------------ compiled cache
    def _stats_for(self, bucket, sig):
        key = (bucket, sig)
        st = self._bucket_stats.get(key)
        if st is None:
            st = self._bucket_stats[key] = _BucketStats()
        return st

    def _breaker_for(self, key):
        """Called with the lock held."""
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = _Breaker(self.breaker_threshold,
                                               self.breaker_cooldown)
        return br

    def _compiled(self, bucket, sig, trace_id=None, warming=False):
        """Per-bucket compiled program; materializes exactly once per
        (bucket, signature) in-process — from the artifact store when
        one is attached and has a verified program (source "store"),
        inline otherwise (source "inline"). Returns (run, source)
        where source is None for an in-process cache hit. Compiles run
        outside the lock (XLA can take seconds; infer submissions must
        not block on them); an in-flight event per key makes racing
        callers (warmup thread, concurrent cold groups) WAIT for the
        one compile instead of burning CPU redoing it N times.
        ``warming`` flows to the runner: warmup may block on a peer
        replica's single-flight compile, the hot path never does.
        ``trace_id`` (a traced request in the group that pays the
        compile) tags the serving.compile span; warmup/untraced
        compiles only feed the summary table."""
        key = (bucket, sig)
        while True:
            with self._lock:
                run = self._cache.get(key)
                if run is not None:
                    return run, None
                ev = self._compiling.get(key)
                if ev is None:
                    ev = self._compiling[key] = threading.Event()
                    mine = True
                else:
                    mine = False
            if not mine:
                # loop: pick up the cached result, or take over as the
                # owner if the first compile failed. Bounded: if the
                # owner's compile wedges, each waiting cold thread must
                # fail its group and EXIT (unbounded ev.wait would leak
                # one permanently-blocked thread per client retry)
                limit = self.cold_compile_timeout
                if limit > 0 and not ev.wait(limit):
                    # retryable: the owner's compile may still land and
                    # cache the program for the caller's next attempt
                    raise RetryableError(
                        f"{self.name}: compile for bucket {bucket} "
                        f"still in flight after cold_compile_timeout="
                        f"{limit}s; retry later")
                elif limit <= 0:
                    # cold_compile_timeout=0 is the operator explicitly
                    # disabling the bound; honour it
                    ev.wait()  # tpu-lint: disable=TPU303  # unbounded wait is the documented timeout-disabled mode
                continue
            try:
                chaos.hit("serving.compile")
                chaos.hit(f"serving.compile.bucket{bucket}")
                t0 = time.monotonic()
                if self._compile_takes_warming:
                    res = self._runner.compile(bucket, sig,
                                               warming=warming)
                else:
                    res = self._runner.compile(bucket, sig)
                run, source = (res if isinstance(res, tuple)
                               else (res, "inline"))
            except BaseException:
                with self._lock:
                    self._compiling.pop(key, None)
                ev.set()
                raise
            dt = time.monotonic() - t0
            if trace_id is not None:
                obs_tracing.record_span("serving.compile", dt,
                                        trace_id=trace_id,
                                        engine=self.name, bucket=bucket,
                                        source=source)
            else:
                obs_tracing.observe("serving.compile", dt)
            with self._lock:
                self._cache[key] = run
                st = self._stats_for(bucket, sig)
                if source == "store":
                    st.store_loads += 1
                else:
                    st.compiles += 1
                self._m_compiles.inc(bucket=str(bucket), source=source)
                self._compiling.pop(key, None)
            ev.set()
            return run, source

    def warmup(self, buckets=None, signature=None):
        """Precompile bucket programs at server start so no request pays
        a compile. Default buckets: every power of two up to
        max_batch_size (plus max itself). Returns the declared list."""
        sig = signature or self._runner.default_signature()
        if sig is None:
            raise ValueError(
                "warmup needs a signature for a callable-backed engine: "
                "pass signature=[(dtype_str, trailing_shape), ...]")
        sig = tuple((np.dtype(dt).str, tuple(tr)) for dt, tr in sig)
        if buckets is None:
            buckets = []
            b = 1
            while b < self.max_batch_size:
                buckets.append(b)
                b <<= 1
            buckets.append(self.max_batch_size)
        buckets = sorted({bucket_rows(int(b), self.max_batch_size)
                          for b in buckets})
        for b in buckets:
            # warming=True: warmup is the single-flight window — N
            # replicas warming the same ladder against a shared
            # artifact store produce ONE compile per bucket (the rest
            # block briefly and load the winner's published program)
            run, source = self._compiled(b, sig, warming=True)
            if source is not None:
                # callable-backed runners compile lazily inside XLA's
                # jit cache: prime with a zero batch so the "no request
                # pays a compile" promise holds there too (no-op for
                # the AOT runner, whose compile() already compiled)
                self._runner.prime(run, b, sig)
        with self._lock:
            self._declared = buckets
        return buckets

    def declared_buckets(self):
        with self._lock:
            return list(self._declared)

    # -------------------------------------------------------------- stats
    def stats(self):
        """Snapshot of engine counters (the `stats` wire command).

        A *view over the obs registry*: every scalar here reads the
        same instruments the Prometheus exposition renders. The whole
        snapshot — registry-backed scalars AND per-bucket tables — is
        taken under one engine-lock acquisition, so a mid-update read
        can never return torn totals (e.g. ``rows`` bumped but
        ``padded`` not yet)."""
        with self._lock:
            buckets = {}
            for (bucket, sig), st in sorted(self._bucket_stats.items(),
                                            key=lambda kv: kv[0][0]):
                d = st.as_dict()
                d["signature"] = [[dt, list(tr)] for dt, tr in sig]
                br = self._breakers.get((bucket, sig))
                if br is not None:
                    d["breaker"] = br.as_dict()
                buckets.setdefault(str(bucket), []).append(d)
            states = [br.state for br in self._breakers.values()]
            return {
                "name": self.name,
                "quant": getattr(self._runner, "quant_mode", None) or "f32",
                "mesh": getattr(self._runner, "mesh_desc", None)
                        or _sharding.SINGLE,
                "max_batch_size": self.max_batch_size,
                "max_wait_ms": round(self.max_wait_s * 1000.0, 3),
                "max_queue": self.max_queue,
                "declared_buckets": list(self._declared),
                "queue_depth": len(self._pending),
                "requests": int(self._m_requests.value()),
                "rows": int(self._m_rows.value()),
                "shed_count": int(self._m_shed.value(reason="queue_full")),
                "quarantine_shed": int(
                    self._m_shed.value(reason="quarantine")),
                "deadline_expired": int(
                    self._m_deadline.value(stage="expired")),
                "deadline_late": int(self._m_deadline.value(stage="late")),
                "scheduler_restarts": int(self._m_restarts.value()),
                "breaker": {
                    "threshold": self.breaker_threshold,
                    "cooldown_s": self.breaker_cooldown,
                    "open": states.count(_Breaker.OPEN),
                    "half_open": states.count(_Breaker.HALF_OPEN),
                    "trips": sum(br.trips
                                 for br in self._breakers.values()),
                },
                "compiles": sum(st.compiles
                                for st in self._bucket_stats.values()),
                "store_loads": sum(st.store_loads
                                   for st in self._bucket_stats.values()),
                "buckets": buckets,
            }

    def stats_json(self):
        return json.dumps(self.stats())

    def health(self):
        """Liveness snapshot for the `health` wire command: is the
        scheduler alive, how stale is its heartbeat, which buckets are
        quarantined, how deep is the queue."""
        now = time.monotonic()
        # store stats walk the artifact directory (file I/O): taken
        # BEFORE the engine lock so a slow disk never stalls the
        # serving hot path behind a health probe (getattr: custom
        # duck-typed runners may predate store_stats)
        store_stats = getattr(self._runner, "store_stats", lambda: None)()
        with self._lock:
            alive = self._scheduler.is_alive()
            quarantined = sorted(
                bucket for (bucket, _sig), br in self._breakers.items()
                if br.state != _Breaker.CLOSED)
            return {
                "ok": alive and not self._closed,
                "closed": self._closed,
                "scheduler_alive": alive,
                "heartbeat_age_s": round(now - self._heartbeat, 3),
                "scheduler_restarts": int(self._m_restarts.value()),
                "queue_depth": len(self._pending),
                "quarantined_buckets": quarantined,
                "cold_compiles_inflight": len(self._cold_inflight),
                "declared_buckets": list(self._declared),
                "mesh": getattr(self._runner, "mesh_desc", None)
                        or _sharding.SINGLE,
                "artifact_store": store_stats,
            }

    # -------------------------------------------------------------- close
    def close(self, timeout=5.0):
        """Stop the scheduler; pending requests still fire (partial
        batches), new submissions raise EngineClosed."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._closed_ev.set()
            self._cond.notify_all()
            sched = self._scheduler
        obs_metrics.REGISTRY.unregister_collector(self._obs_collector)
        sched.join(timeout)
        if self._watchdog is not None:
            self._watchdog.join(timeout)
        with self._lock:
            colds = list(self._cold_threads)
            self._cold_threads = []
        for t in colds:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
