"""Dynamic-batching serving engine: coalesce concurrent infer requests
into padded shape-bucket batches over one compiled-program cache.

On TPU, serving throughput comes almost entirely from batch parallelism
and from amortizing XLA compilation over stable shapes — a
thread-per-request predictor pays full dispatch per sample and a full
compile per novel shape. This engine is the runtime complement to
tracelint's static recompilation-hazard passes (TPU101-TPU104):

  requests --> bounded queue --> scheduler thread --> padded bucket batch
                (load shed)       (fire on max_batch_size                 \
                                   or max_wait_ms)                         --> per-bucket
                                                                               AOT-compiled
  response <-- slice rows off <---------------------------------------------- program

Shape buckets are powers of two (clamped to ``max_batch_size``): padding
the coalesced row count up to the next bucket means each bucket's
program compiles exactly once, no matter what request mix arrives.
Declared buckets are precompiled by :meth:`BatchingEngine.warmup` so the
first real request never eats a compile. The bounded queue plus
:class:`EngineOverloaded` (wire status ``2``) turn saturation into fast
rejection — load shedding — instead of unbounded memory growth.

Determinism contract (verified in tests/test_serving_batching.py):
engine outputs are bitwise identical to unbatched ``Predictor.run`` for
any request of >= 2 rows and for all integer dtypes — padding rows are
sliced off before anything is returned, and XLA's row-independent
programs are bitwise row-stable across batch sizes >= 2 on CPU. The one
carve-out: XLA lowers batch-1 float matmuls to a gemv with different
rounding than the gemm used for batch >= 2, so a COALESCED 1-row float
request can differ from its solo baseline in the last ulp (a solo 1-row
request fires at bucket 1 — the same program as the baseline — and stays
bitwise equal). A 1-row tail chunk of a split oversized request pads to
bucket 2 for the same reason: its rows came from a >= 2-row baseline
dispatch, so it must stay in the gemm regime.
"""
import json
import threading
import time
import warnings

import numpy as np

# Wire status byte for a shed request (server.py speaks it; defined here
# so the engine has no import-time dependency on the server).
OVERLOADED_STATUS = 2


class EngineOverloaded(RuntimeError):
    """Raised by submit/infer when the bounded queue is full: the caller
    should back off (the server maps this to wire status 2)."""

    status_code = OVERLOADED_STATUS


class EngineClosed(RuntimeError):
    pass


def bucket_rows(n, max_batch_size):
    """Next power-of-2 >= n, clamped to max_batch_size."""
    if n <= 0:
        raise ValueError(f"need at least one row, got {n}")
    if n >= max_batch_size:
        return max_batch_size
    return min(max_batch_size, 1 << (n - 1).bit_length())


def _signature(arrays):
    """Batch-compatibility key: dtype + trailing dims of every input
    (requests coalesce only when everything but the row count matches)."""
    return tuple((a.dtype.str, a.shape[1:]) for a in arrays)


class _Request:
    __slots__ = ("inputs", "rows", "sig", "event", "outputs", "error",
                 "t_enqueue", "min_bucket")

    def __init__(self, inputs, rows, sig, min_bucket=1):
        self.inputs = inputs
        self.rows = rows
        self.sig = sig
        self.event = threading.Event()
        self.outputs = None
        self.error = None
        self.t_enqueue = time.monotonic()
        # split chunks of a >= 2-row request carry min_bucket=2: a solo
        # 1-row tail chunk must still fire in the batch >= 2 regime
        # (bucket 1 is XLA's gemv regime, which rounds differently) to
        # keep the split path bitwise equal to the unbatched baseline
        self.min_bucket = min_bucket


class _BucketStats:
    __slots__ = ("compiles", "batches", "requests", "rows", "padded_rows",
                 "total_ms", "max_ms")

    def __init__(self):
        self.compiles = 0
        self.batches = 0
        self.requests = 0
        self.rows = 0
        self.padded_rows = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def as_dict(self):
        return {
            "compiles": self.compiles,
            "batches": self.batches,
            "requests": self.requests,
            "rows": self.rows,
            "padded_rows": self.padded_rows,
            "total_ms": round(self.total_ms, 3),
            "avg_ms": round(self.total_ms / self.batches, 3)
                      if self.batches else 0.0,
            "max_ms": round(self.max_ms, 3),
        }


class AotLayerRunner:
    """Execute batches for a jit-loaded :class:`TranslatedLayer` through
    per-bucket ahead-of-time compiled programs.

    The layer's exported StableHLO must be batch-polymorphic in dim 0 of
    every input (``jit.save`` with ``InputSpec([None, ...])``); each
    bucket is then lowered+compiled exactly once with the weights passed
    as runtime arguments (shared on device across buckets, never baked
    into the program) and the batch buffers donated.
    """

    def __init__(self, layer, donate=True):
        import jax

        self._jax = jax
        self._layer = layer
        self._donate = donate
        specs = getattr(layer, "_input_specs", None) or []
        if not specs:
            raise ValueError("layer has no input specs; was it jit-saved?")
        if not getattr(layer, "_polymorphic", False):
            raise ValueError(
                "dynamic batching needs a batch-polymorphic saved model: "
                "re-save with paddle.jit.save(..., input_spec="
                "[InputSpec([None, ...], dtype)]) so dim 0 exports as a "
                "symbolic size (BatchingEngine.for_callable is the "
                "fallback for fixed-shape models)")
        self._trailing = []
        self._dtypes = []
        for shape, dtype in specs:
            if shape and shape[0] is not None:
                raise ValueError(
                    f"input spec {shape} has a concrete dim 0; every "
                    "input must be batch-polymorphic for bucket batching")
            if any(d is None for d in shape[1:]):
                raise ValueError(
                    f"input spec {shape} has a symbolic non-batch dim; "
                    "the batching engine buckets dim 0 only — re-save "
                    "with concrete trailing dims (or pad/bucket those "
                    "dims client-side before submitting)")
            self._trailing.append(tuple(int(d) for d in shape[1:]))
            self._dtypes.append(np.dtype(dtype))

    def default_signature(self):
        """The saved model's batch signature (for warmup)."""
        return tuple((dt.str, tr)
                     for dt, tr in zip(self._dtypes, self._trailing))

    def compile(self, bucket, sig):
        """Lower + compile the bucket's program. Called once per bucket
        by the engine's cache; the compiled callable takes the padded
        numpy batch arrays and returns a list of numpy outputs."""
        jax = self._jax
        layer = self._layer
        n_in = len(sig)

        def flat_fn(param_list, buffer_list, *inputs):
            out = layer._call_fn(param_list, buffer_list, *inputs)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        param_arrays = [p._value for p in layer._parameters.values()]
        buffer_arrays = [jax.numpy.asarray(b)
                         for b in layer._loaded_buffers.values()]
        param_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                       for a in param_arrays]
        buffer_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in buffer_arrays]
        in_specs = [jax.ShapeDtypeStruct((bucket,) + tr, np.dtype(dt))
                    for dt, tr in sig]
        donate = tuple(range(2, 2 + n_in)) if self._donate else ()
        with warnings.catch_warnings():
            # tiny models may leave a donated batch buffer unused; that
            # is an optimization miss, not an error worth a warning per
            # compile
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = (jax.jit(flat_fn, donate_argnums=donate)
                        .lower(param_specs, buffer_specs, *in_specs)
                        .compile())

        def run(batch_arrays):
            out = compiled(param_arrays, buffer_arrays, *batch_arrays)
            # np.asarray is the device->host readback: the true sync
            # point (PERF.md), and the bytes the server will encode
            return [np.asarray(o) for o in out]

        return run

    def prime(self, run, bucket, sig):
        """No-op: compile() above already AOT-compiled the program."""


class CallableRunner:
    """Fallback runner wrapping any ``fn(*arrays) -> list[array]`` (e.g.
    a fixed-shape model or a plain python function). There is no AOT
    cache to manage — the bucket's real compile happens inside XLA's
    own jit cache on the first batch executed at that size, so
    ``warmup`` primes each bucket by running a zero batch through it."""

    def __init__(self, fn):
        self._fn = fn

    def default_signature(self):
        return None

    def compile(self, bucket, sig):
        fn = self._fn

        def run(batch_arrays):
            out = fn(*batch_arrays)
            if not isinstance(out, (list, tuple)):
                out = [out]
            return [np.asarray(o._value if hasattr(o, "_value") else o)
                    for o in out]

        return run

    def prime(self, run, bucket, sig):
        """Execute a zero batch so XLA traces+compiles this bucket now,
        not on the first real request."""
        run([np.zeros((bucket,) + tuple(tr), np.dtype(dt))
             for dt, tr in sig])


class BatchingEngine:
    """Shared dynamic-batching front end for a served model.

    ``infer(inputs)`` blocks the calling thread until its rows come back
    from a coalesced batch; any number of threads (server handlers,
    cloned predictors) may call it concurrently. Construction::

        engine = BatchingEngine.for_layer(layer, max_batch_size=32,
                                          max_wait_ms=2.0, max_queue=256)
        engine.warmup()            # precompile all power-of-2 buckets
        outs = engine.infer([x])   # x: [rows, ...]; rows <= max splits

    Knobs:
      max_batch_size  cap on coalesced rows per fired batch (the
                      Config.enable_tensorrt_engine(max_batch_size=...)
                      knob routes here on TPU)
      max_wait_ms     scheduler fires a partial batch once the oldest
                      pending request has waited this long
      max_queue       bounded pending-request cap; beyond it submit()
                      sheds with EngineOverloaded (wire status 2)
    """

    def __init__(self, runner, max_batch_size=32, max_wait_ms=2.0,
                 max_queue=256, name="engine"):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._runner = runner
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.name = name
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = []  # FIFO of _Request
        self._cache = {}  # (bucket, sig) -> compiled run callable
        self._compiling = {}  # (bucket, sig) -> Event for in-flight compile
        self._bucket_stats = {}  # (bucket, sig) -> _BucketStats
        self._shed_count = 0
        self._n_requests = 0
        self._n_rows = 0
        self._declared = []  # bucket row counts from warmup()
        self._cold_threads = []  # in-flight cold-bucket compile threads
        self._closed = False
        self._scheduler = threading.Thread(target=self._run_scheduler,
                                           name=f"{name}-scheduler",
                                           daemon=True)
        self._scheduler.start()

    # ------------------------------------------------------- constructors
    @classmethod
    def for_layer(cls, layer, donate=True, **kw):
        """Engine over a jit-loaded batch-polymorphic TranslatedLayer
        (per-bucket AOT compile, donation on the batch buffers)."""
        return cls(AotLayerRunner(layer, donate=donate), **kw)

    @classmethod
    def for_callable(cls, fn, **kw):
        """Engine over any ``fn(*arrays) -> outputs`` callable."""
        return cls(CallableRunner(fn), **kw)

    # ------------------------------------------------------------- submit
    def infer(self, inputs, timeout=None):
        """Run one request (list of arrays sharing dim 0 = rows) through
        the engine; returns the list of output arrays for those rows.

        Requests larger than max_batch_size are split into chunks and
        re-joined (the split path); each chunk occupies its own queue
        slot so an oversized request cannot bypass the shed cap.
        """
        inputs = [np.ascontiguousarray(a) for a in inputs]
        if not inputs:
            raise ValueError("infer() needs at least one input array")
        rows = int(inputs[0].shape[0]) if inputs[0].ndim else 0
        if rows <= 0:
            raise ValueError("inputs must have a leading batch dim >= 1")
        for a in inputs:
            if a.ndim == 0 or a.shape[0] != rows:
                raise ValueError(
                    "all inputs of one request must share dim 0 "
                    f"(got {[tuple(x.shape) for x in inputs]})")
        if rows > self.max_batch_size:
            return self._infer_split(inputs, rows, timeout)
        req = self._submit(inputs, rows)
        return self._wait(req, timeout)

    def _infer_split(self, inputs, rows, timeout):
        n_chunks = -(-rows // self.max_batch_size)
        if n_chunks > self.max_queue:
            # a deterministic can-never-fit request must get a permanent
            # error, not EngineOverloaded: status 2 tells clients to back
            # off and RETRY, and this retry can never succeed
            raise ValueError(
                f"request of {rows} rows needs {n_chunks} chunks of "
                f"max_batch_size={self.max_batch_size} but the queue cap "
                f"is {self.max_queue}: split the request client-side or "
                "raise max_queue/max_batch_size")
        chunks = []
        for lo in range(0, rows, self.max_batch_size):
            hi = min(rows, lo + self.max_batch_size)
            chunks.append([a[lo:hi] for a in inputs])
        # all chunks are enqueued atomically: a partially-admitted
        # oversized request would compute rows only to discard them
        # when a later chunk sheds
        reqs = self._submit_chunks(
            chunks, min_bucket=min(2, self.max_batch_size))
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        parts = []
        for r in reqs:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            parts.append(self._wait(r, left))
        return [np.concatenate([p[i] for p in parts])
                for i in range(len(parts[0]))]

    def _submit(self, inputs, rows):
        return self._submit_chunks([inputs])[0]

    def _submit_chunks(self, chunks, min_bucket=1):
        """Admit every chunk or none (one queue slot per chunk, so an
        oversized request still counts fully against the shed cap)."""
        with self._cond:
            if self._closed:
                raise EngineClosed(f"{self.name} is closed")
            if len(self._pending) + len(chunks) > self.max_queue:
                self._shed_count += 1
                raise EngineOverloaded(
                    f"{self.name} queue full ({len(self._pending)} pending,"
                    f" cap {self.max_queue}, need {len(chunks)} slots); "
                    "request shed")
            reqs = []
            for chunk in chunks:
                rows = int(chunk[0].shape[0])
                req = _Request(chunk, rows, _signature(chunk), min_bucket)
                self._pending.append(req)
                self._n_requests += 1
                self._n_rows += rows
                reqs.append(req)
            self._cond.notify_all()
        return reqs

    @staticmethod
    def _wait(req, timeout):
        if not req.event.wait(timeout):
            raise TimeoutError("engine did not answer within timeout")
        if req.error is not None:
            raise req.error
        return req.outputs

    # ---------------------------------------------------------- scheduler
    def _run_scheduler(self):
        while True:
            group = self._next_group()
            if group is None:
                return  # closed and drained
            key = (self._group_bucket(group), group[0].sig)
            with self._lock:
                cold = key not in self._cache
            if cold:
                # a cold bucket pays a multi-second XLA compile: run it
                # on its own thread so already-compiled buckets keep
                # flowing instead of stalling head-of-line behind it
                t = threading.Thread(target=self._run_group_guarded,
                                     args=(group,),
                                     name=f"{self.name}-cold-compile",
                                     daemon=True)
                with self._lock:
                    self._cold_threads = [x for x in self._cold_threads
                                          if x.is_alive()]
                    self._cold_threads.append(t)
                t.start()
            else:
                self._run_group_guarded(group)

    def _run_group_guarded(self, group):
        try:
            self._run_group(group)
        except Exception as e:  # noqa: BLE001 - fail the group only
            for r in group:
                r.error = e
                r.event.set()

    def _next_group(self):
        """Block until a same-signature group is ready to fire: either
        max_batch_size rows are pending or the oldest request has waited
        max_wait_ms. Returns the popped group (None = engine closed)."""
        with self._cond:
            while True:
                if not self._pending:
                    if self._closed:
                        return None
                    self._cond.wait()
                    continue
                head = self._pending[0]
                group, rows = [], 0
                for r in self._pending:
                    if r.sig != head.sig:
                        continue
                    if rows + r.rows > self.max_batch_size:
                        break
                    group.append(r)
                    rows += r.rows
                deadline = head.t_enqueue + self.max_wait_s
                now = time.monotonic()
                if (rows >= self.max_batch_size or now >= deadline
                        or self._closed):
                    for r in group:
                        self._pending.remove(r)
                    return group
                self._cond.wait(deadline - now)

    def _group_bucket(self, group):
        """Bucket for a popped group: next power of two over the
        coalesced rows, floored by any chunk's min_bucket (a solo
        1-row split tail pads to bucket 2 to stay in the bitwise-stable
        batch >= 2 regime)."""
        want = max(sum(r.rows for r in group),
                   max(r.min_bucket for r in group))
        return bucket_rows(want, self.max_batch_size)

    def _run_group(self, group):
        rows = sum(r.rows for r in group)
        sig = group[0].sig
        bucket = self._group_bucket(group)
        run, _ = self._compiled(bucket, sig)
        n_in = len(sig)
        batch = []
        for i in range(n_in):
            parts = [r.inputs[i] for r in group]
            if bucket > rows:
                pad_shape = (bucket - rows,) + parts[0].shape[1:]
                parts.append(np.zeros(pad_shape, parts[0].dtype))
            batch.append(np.concatenate(parts) if len(parts) > 1
                         else parts[0])
        t0 = time.monotonic()
        outs = run(batch)
        dt_ms = (time.monotonic() - t0) * 1000.0
        for j, o in enumerate(outs):
            if getattr(o, "ndim", 0) == 0 or o.shape[0] != bucket:
                raise ValueError(
                    f"output {j} has shape {tuple(getattr(o, 'shape', ()))}"
                    f" but the batch has {bucket} rows: every output must "
                    "keep the batch dim as dim 0 so per-request rows can "
                    "be sliced back — batch-reduced outputs cannot go "
                    "through the batching engine")
        off = 0
        for r in group:
            r.outputs = [o[off:off + r.rows] for o in outs]
            off += r.rows
            r.event.set()
        with self._lock:
            st = self._stats_for(bucket, sig)
            st.batches += 1
            st.requests += len(group)
            st.rows += rows
            st.padded_rows += bucket - rows
            st.total_ms += dt_ms
            st.max_ms = max(st.max_ms, dt_ms)

    # ------------------------------------------------------ compiled cache
    def _stats_for(self, bucket, sig):
        key = (bucket, sig)
        st = self._bucket_stats.get(key)
        if st is None:
            st = self._bucket_stats[key] = _BucketStats()
        return st

    def _compiled(self, bucket, sig):
        """Per-bucket compiled program; compiles exactly once per
        (bucket, signature). Compiles run outside the lock (XLA can
        take seconds; infer submissions must not block on them); an
        in-flight event per key makes racing callers (warmup thread,
        concurrent cold groups) WAIT for the one compile instead of
        burning CPU redoing it N times."""
        key = (bucket, sig)
        while True:
            with self._lock:
                run = self._cache.get(key)
                if run is not None:
                    return run, False
                ev = self._compiling.get(key)
                if ev is None:
                    ev = self._compiling[key] = threading.Event()
                    mine = True
                else:
                    mine = False
            if not mine:
                # loop: pick up the cached result, or take over as the
                # owner if the first compile failed
                ev.wait()
                continue
            try:
                run = self._runner.compile(bucket, sig)
            except BaseException:
                with self._lock:
                    self._compiling.pop(key, None)
                ev.set()
                raise
            with self._lock:
                self._cache[key] = run
                self._stats_for(bucket, sig).compiles += 1
                self._compiling.pop(key, None)
            ev.set()
            return run, True

    def warmup(self, buckets=None, signature=None):
        """Precompile bucket programs at server start so no request pays
        a compile. Default buckets: every power of two up to
        max_batch_size (plus max itself). Returns the declared list."""
        sig = signature or self._runner.default_signature()
        if sig is None:
            raise ValueError(
                "warmup needs a signature for a callable-backed engine: "
                "pass signature=[(dtype_str, trailing_shape), ...]")
        sig = tuple((np.dtype(dt).str, tuple(tr)) for dt, tr in sig)
        if buckets is None:
            buckets = []
            b = 1
            while b < self.max_batch_size:
                buckets.append(b)
                b <<= 1
            buckets.append(self.max_batch_size)
        buckets = sorted({bucket_rows(int(b), self.max_batch_size)
                          for b in buckets})
        for b in buckets:
            run, fresh = self._compiled(b, sig)
            if fresh:
                # callable-backed runners compile lazily inside XLA's
                # jit cache: prime with a zero batch so the "no request
                # pays a compile" promise holds there too (no-op for
                # the AOT runner, whose compile() already compiled)
                self._runner.prime(run, b, sig)
        with self._lock:
            self._declared = buckets
        return buckets

    # -------------------------------------------------------------- stats
    def stats(self):
        """Snapshot of engine counters (the `stats` wire command)."""
        with self._lock:
            buckets = {}
            for (bucket, sig), st in sorted(self._bucket_stats.items(),
                                            key=lambda kv: kv[0][0]):
                d = st.as_dict()
                d["signature"] = [[dt, list(tr)] for dt, tr in sig]
                buckets.setdefault(str(bucket), []).append(d)
            return {
                "name": self.name,
                "max_batch_size": self.max_batch_size,
                "max_wait_ms": round(self.max_wait_s * 1000.0, 3),
                "max_queue": self.max_queue,
                "declared_buckets": list(self._declared),
                "queue_depth": len(self._pending),
                "requests": self._n_requests,
                "rows": self._n_rows,
                "shed_count": self._shed_count,
                "compiles": sum(st.compiles
                                for st in self._bucket_stats.values()),
                "buckets": buckets,
            }

    def stats_json(self):
        return json.dumps(self.stats())

    # -------------------------------------------------------------- close
    def close(self, timeout=5.0):
        """Stop the scheduler; pending requests still fire (partial
        batches), new submissions raise EngineClosed."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._scheduler.join(timeout)
        with self._lock:
            colds = list(self._cold_threads)
            self._cold_threads = []
        for t in colds:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
