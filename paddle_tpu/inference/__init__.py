"""paddle.inference — deployment API (reference: paddle/fluid/inference/
api/analysis_predictor.cc AnalysisPredictor, api/paddle_api.h,
paddle_inference_api.h Config/Predictor/Tensor).

TPU-native design: the reference's analysis pipeline (ir fusion passes,
memory-optimize, TensorRT/Lite subgraph capture) collapses into XLA —
models are stored as serialized StableHLO (jax.export) produced by
``paddle.jit.save`` / ``paddle.static.save_inference_model``, and the
predictor compiles them once per input-shape signature, then runs with
device-resident inputs/outputs (the ZeroCopyRun analog).
"""
from .config import Config, PrecisionType, PlaceType
from .predictor import Predictor, Tensor as PredictorTensor, create_predictor
from .predictor import Tensor  # noqa: F401 (reference exports it plainly)


class DataType:
    """reference: paddle_infer.DataType enum."""

    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4


__all__ = [
    "Config", "DataType", "Predictor", "PredictorTensor", "Tensor",
    "create_predictor",
    "PrecisionType", "PlaceType",
]
