"""paddle.inference — deployment API (reference: paddle/fluid/inference/
api/analysis_predictor.cc AnalysisPredictor, api/paddle_api.h,
paddle_inference_api.h Config/Predictor/Tensor).

TPU-native design: the reference's analysis pipeline (ir fusion passes,
memory-optimize, TensorRT/Lite subgraph capture) collapses into XLA —
models are stored as serialized StableHLO (jax.export) produced by
``paddle.jit.save`` / ``paddle.static.save_inference_model``, and the
predictor compiles them once per input-shape signature, then runs with
device-resident inputs/outputs (the ZeroCopyRun analog).

The serving wire protocol's machine-readable spec lives in
``paddle_tpu.inference.wire_spec`` (commands, statuses, markers, dtype
table, codec, error taxonomy) — the compatibility reference for
external clients and the table the ``--protocol`` lint diffs every
implementation against.
"""
from .config import Config, PrecisionType, PlaceType
from .predictor import Predictor, Tensor as PredictorTensor, create_predictor
from .predictor import Tensor  # noqa: F401 (reference exports it plainly)


class DataType:
    """reference: paddle_infer.DataType enum."""

    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4


__all__ = [
    "Config", "DataType", "Predictor", "PredictorTensor", "Tensor",
    "create_predictor",
    "PrecisionType", "PlaceType",
    # fleet tier (lazy: importing paddle_tpu.inference must not pull
    # in the router/registry threads' modules until asked)
    "Fleet", "FleetRouter", "ReplicaRegistry", "TenantPolicy",
    "Autoscaler", "subprocess_spawner", "tenant_id",
    # continuous-batching decode (lazy for the same reason)
    "DecodeEngine", "DecodeModel", "DecodeRequest",
    # sharded multi-chip serving (lazy: sharding builds no state at
    # import, but keeps the package surface consistent)
    "ServingMesh",
]

_FLEET_HOMES = {
    "Fleet": "fleet", "Autoscaler": "fleet",
    "subprocess_spawner": "fleet", "ReplicaHandle": "fleet",
    "FleetRouter": "router", "TenantPolicy": "router",
    "FairGate": "router", "tenant_id": "router",
    "ReplicaRegistry": "registry",
    "DecodeEngine": "decode", "DecodeModel": "decode",
    "DecodeRequest": "decode",
    "ServingMesh": "sharding",
}


def __getattr__(name):
    home = _FLEET_HOMES.get(name)
    if home is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{home}", __name__), name)
