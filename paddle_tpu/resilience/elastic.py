"""Elastic pod-scale training: preemption consensus, straggler
detection, and host-loss recovery over a small TCP coordinator.

PR 2's resilience runtime is single-host: a SIGTERM'd trainer saves and
exits alone. At pod scale that tears the checkpoint — every rank must
save the SAME step or the sharded checkpoint mixes optimizer states
from different steps. The fleet papers (PAPERS.md "ML Productivity
Goodput", "Limits of Concurrency on TPUs") add two more failure shapes
that dominate lost time at scale: slow hosts (stragglers) and dead
hosts (preemption without the grace signal).

This module provides both halves of the protocol:

- :class:`ElasticCoordinator` — rank 0 owns it; a tiny threaded TCP
  server (newline-delimited JSON, one request per connection, mirroring
  ``launch_collective``'s rendezvous shape) tracking per-rank
  heartbeats (step, step duration), straggler flags, dead hosts, the
  preemption-consensus state machine, and named barriers.
- :class:`ElasticClient` — every rank (including 0) connects as a
  client; a daemon heartbeat thread gossips (step, step_s) and relays
  the local :class:`~.preemption.PreemptionHandler`'s requested flag;
  the training loop calls :meth:`ElasticClient.note_step` +
  :meth:`ElasticClient.check_boundary` at every step boundary.

Consensus protocol (documented in README "Elastic training"):

1. Any trigger — a rank's SIGTERM handler fires, a host misses
   heartbeats past ``dead_timeout``, or a programmatic
   :meth:`ElasticClient.request_save` — flips the coordinator into
   ``save_requested``.
2. Each ALIVE rank, at its next step boundary, proposes the step it has
   just completed and blocks (polling, bounded by
   ``consensus_timeout``) until consensus resolves.
3. Once every alive rank has proposed, consensus = max(proposals): the
   highest boundary any rank has already reached. Ranks behind it train
   the missing steps (collectives stay matched — every global step index
   executes exactly once on every rank), then all save step C, barrier,
   and exit 143 together. No torn multi-host checkpoints.

Straggler detection reuses PR 5's watchdog pattern on gossip: a host
whose latest step duration exceeds ``straggler_k`` x the pod median for
``straggler_n`` consecutive steps is flagged (counter + log) — flagged,
never killed: at pod scale a slow host is an operator page, not a
crash.

Env knobs (all ``PADDLE_TPU_ELASTIC_*``):

    PADDLE_TPU_ELASTIC_COORD         host:port of the coordinator
                                     (set per attempt by launch_collective)
    PADDLE_TPU_ELASTIC_HB_INTERVAL   heartbeat period, s     (0.5)
    PADDLE_TPU_ELASTIC_DEAD_TIMEOUT  missed-heartbeat window, s (10)
    PADDLE_TPU_ELASTIC_STRAGGLER_K   slowdown multiplier     (3.0)
    PADDLE_TPU_ELASTIC_STRAGGLER_N   consecutive strikes     (3)
    PADDLE_TPU_ELASTIC_CONSENSUS_TIMEOUT  propose wait, s    (60)
    PADDLE_TPU_ELASTIC_BARRIER_TIMEOUT    barrier wait, s    (120)
    PADDLE_TPU_ELASTIC_EXIT_GRACE    launcher consensus-exit grace (30)
"""
import json
import os
import socket
import socketserver
import sys
import threading
import time

from ..obs import goodput as _goodput
from ..obs import metrics as _obs
from . import preemption
from .retry import _env_float, _env_int, call_with_retry

ENV_COORD = "PADDLE_TPU_ELASTIC_COORD"

_CONSENSUS_SAVES = _obs.counter(
    "paddle_elastic_consensus_saves_total",
    "Multi-host preemption consensus rounds resolved")
_DEAD_HOSTS = _obs.counter(
    "paddle_elastic_dead_hosts_total",
    "Hosts declared dead after missing heartbeats")
_STRAGGLERS = _obs.counter(
    "paddle_elastic_stragglers_total",
    "Hosts flagged as stragglers (k*median for n consecutive steps)")


def _log(msg):
    print(f"[elastic] {msg}", file=sys.stderr, flush=True)


class ElasticError(RuntimeError):
    """Base for elastic-protocol failures."""


class CoordinatorLost(ElasticError):
    """The coordinator stopped answering: save solo is torn, so the
    caller should exit 143 WITHOUT saving and resume from the last
    published checkpoint."""


class ConsensusTimeout(ElasticError):
    """Consensus did not resolve within consensus_timeout."""


# --------------------------------------------------------------- coordinator

class _CoordServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class _CoordHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline(1 << 20)
            if not line:
                return
            msg = json.loads(line.decode("utf-8"))
            reply = self.server.coordinator.handle(msg)
            self.wfile.write(json.dumps(reply).encode("utf-8") + b"\n")
        except (OSError, ValueError):
            pass  # a dying peer mid-request; the protocol is idempotent


class ElasticCoordinator:
    """Rank-0 pod brain: heartbeats, stragglers, dead hosts, consensus.

    All state transitions happen inside :meth:`handle` under one lock;
    socket I/O stays in the per-connection handler threads OUTSIDE the
    lock. Dead-host detection is lazy — evaluated on every incoming
    request — so no extra monitor thread is needed: while any rank
    lives, its heartbeats drive the clock; if all die, the launcher's
    watch loop owns the outcome.
    """

    def __init__(self, world, host="127.0.0.1", port=0, dead_timeout=None,
                 straggler_k=None, straggler_n=None):
        self.world = int(world)
        self.dead_timeout = (_env_float("PADDLE_TPU_ELASTIC_DEAD_TIMEOUT",
                                        10.0)
                             if dead_timeout is None else float(dead_timeout))
        self.straggler_k = (_env_float("PADDLE_TPU_ELASTIC_STRAGGLER_K", 3.0)
                            if straggler_k is None else float(straggler_k))
        self.straggler_n = (_env_int("PADDLE_TPU_ELASTIC_STRAGGLER_N", 3)
                            if straggler_n is None else int(straggler_n))
        self._lock = threading.Lock()
        now = time.monotonic()
        # every expected rank starts "alive as of now": a rank that
        # never says hello still dies after dead_timeout, so a crash
        # during startup cannot hang barriers forever
        self._ranks = {r: {"step": 0, "t_hb": now, "step_s": None,
                           "strikes": 0, "straggler": False}
                       for r in range(self.world)}
        self._dead = set()
        self._save_requested = False
        self._save_reason = None
        self._proposals = {}
        self._margins = {}
        self._consensus = None
        self._barriers = {}
        self._saved = {}
        self._finished = {}
        self._server = _CoordServer((host, int(port)), _CoordHandler)
        self._server.coordinator = self
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        kwargs={"poll_interval": 0.1},
                                        name="elastic-coordinator",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._server.server_address[1]

    @property
    def address(self):
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    # ----------------------------------------------------------- state ops
    def _check_dead(self, now):
        # caller holds self._lock
        for r, info in self._ranks.items():
            if r in self._dead:
                continue
            if now - info["t_hb"] > self.dead_timeout:
                self._dead.add(r)
                self._proposals.pop(r, None)
                _DEAD_HOSTS.inc()
                _log(f"rank {r} declared dead "
                     f"(no heartbeat for {self.dead_timeout:.1f}s)")
                if not self._save_requested:
                    self._save_requested = True
                    self._save_reason = f"dead_host:{r}"

    def _alive(self):
        return [r for r in self._ranks if r not in self._dead]

    def _maybe_consensus(self):
        # caller holds self._lock. consensus = max(latest proposals)
        # [+ margin]: blocking clients (collective-free training) stop
        # at their proposal, so margin 0 and the max IS reachable by
        # every rank; non-blocking clients (collective training, where
        # stopping to wait would wedge the peers inside the next step's
        # collective) keep training while consensus resolves, so the
        # barrier is pushed `margin` steps into the future — with
        # per-step synchronisation the skew a rank can accumulate
        # before its next boundary check is < margin, so no rank can
        # overshoot the agreed step
        if not self._save_requested or self._consensus is not None:
            return
        alive = self._alive()
        if alive and all(r in self._proposals for r in alive):
            margin = max((self._margins.get(r, 0) for r in alive),
                         default=0)
            self._consensus = max(self._proposals[r]
                                  for r in alive) + margin
            _CONSENSUS_SAVES.inc()
            _log(f"consensus save at step {self._consensus} "
                 f"({self._save_reason}; proposals {self._proposals}, "
                 f"margin {margin})")

    def _note_straggler(self, rank, step_s):
        # caller holds self._lock. One sample per completed step; the
        # pod median comes from every rank's LATEST step duration.
        info = self._ranks[rank]
        info["step_s"] = step_s
        # median over the OTHER alive ranks: judging a host against a
        # median that includes its own sample hides the straggler in
        # small pods (2 hosts -> the slow one IS the upper median)
        samples = sorted(i["step_s"] for r, i in self._ranks.items()
                         if r != rank and r not in self._dead
                         and i["step_s"] is not None)
        if not samples:
            return
        mid = len(samples) // 2
        median = (samples[mid] if len(samples) % 2
                  else 0.5 * (samples[mid - 1] + samples[mid]))
        if median > 0 and step_s > self.straggler_k * median:
            info["strikes"] += 1
            if info["strikes"] >= self.straggler_n and not info["straggler"]:
                info["straggler"] = True
                _STRAGGLERS.inc()
                _log(f"rank {rank} flagged as straggler: step {step_s:.3f}s"
                     f" > {self.straggler_k:.1f} x median {median:.3f}s for "
                     f"{info['strikes']} consecutive steps")
        else:
            if info["straggler"]:
                _log(f"rank {rank} recovered: step {step_s:.3f}s back "
                     f"under {self.straggler_k:.1f} x median {median:.3f}s")
            info["strikes"] = 0
            info["straggler"] = False  # recovers when it stops lagging

    def _view(self):
        # caller holds self._lock
        return {"save": self._save_requested,
                "reason": self._save_reason,
                "consensus": self._consensus,
                "dead": sorted(self._dead),
                "stragglers": sorted(r for r, i in self._ranks.items()
                                     if i["straggler"])}

    # ------------------------------------------------------------ protocol
    def handle(self, msg):
        op = msg.get("type")
        rank = int(msg.get("rank", -1))
        now = time.monotonic()
        with self._lock:
            self._check_dead(now)
            if rank in self._ranks:
                self._ranks[rank]["t_hb"] = now
                self._dead.discard(rank)  # a flapping host came back
            if op == "hello":
                return {"ok": True, "world": self.world}
            if op == "hb":
                info = self._ranks.get(rank)
                if info is not None:
                    step = int(msg.get("step", info["step"]))
                    info["step"] = max(info["step"], step)
                    if msg.get("step_s") is not None:
                        self._note_straggler(rank, float(msg["step_s"]))
                if msg.get("preempt") and not self._save_requested:
                    self._save_requested = True
                    self._save_reason = f"preempt:{rank}"
                    _log(f"rank {rank} requested preemption save")
                self._maybe_consensus()
                return self._view()
            if op == "request_save":
                if not self._save_requested:
                    self._save_requested = True
                    self._save_reason = msg.get("reason") or f"request:{rank}"
                self._maybe_consensus()
                return self._view()
            if op == "propose":
                if rank not in self._dead and rank in self._ranks:
                    step = int(msg["step"])
                    prev = self._proposals.get(rank)
                    self._proposals[rank] = max(step, prev or 0)
                    self._margins[rank] = int(msg.get("margin", 0))
                self._maybe_consensus()
                return self._view()
            if op == "barrier":
                arrived = self._barriers.setdefault(str(msg["name"]), set())
                arrived.add(rank)
                alive = set(self._alive())
                return {"done": alive <= arrived, "n": len(arrived)}
            if op == "barrier_status":
                arrived = self._barriers.get(str(msg["name"]), set())
                alive = set(self._alive())
                return {"done": bool(arrived) and alive <= arrived,
                        "n": len(arrived)}
            if op == "finished":
                # a rank that completed its workload: it stops polling
                # check_boundary, so it stands as a PERMANENT proposal
                # at its final step — a consensus triggered afterwards
                # (straggler still training + a host dies) resolves to
                # max(final steps) instead of stalling on a rank that
                # will never propose again
                if rank in self._ranks:
                    step = int(msg.get("step", 0))
                    self._finished[rank] = step
                    prev = self._proposals.get(rank)
                    self._proposals[rank] = max(step, prev or 0)
                self._maybe_consensus()
                view = self._view()
                alive = set(self._alive())
                view["done"] = alive <= set(self._finished)
                return view
            if op == "saved":
                self._saved[rank] = int(msg["step"])
                return {"ok": True}
            if op == "status":
                view = self._view()
                view["ranks"] = {str(r): {"step": i["step"],
                                          "step_s": i["step_s"],
                                          "straggler": i["straggler"],
                                          "age_s": round(now - i["t_hb"], 3)}
                                 for r, i in self._ranks.items()}
                view["saved"] = dict(self._saved)
                view["proposals"] = dict(self._proposals)
                return view
        return {"error": f"unknown op {op!r}"}


# -------------------------------------------------------------------- client

class ElasticClient:
    """Per-rank handle on the pod coordinator.

    The training loop calls ``note_step(step, seconds)`` after every
    completed step and then ``check_boundary(step)``; a non-None return
    C means "save at step C and exit 143" — keep training until
    ``step >= C`` first. The heartbeat thread gossips in the
    background and relays the local preemption handler, so a SIGTERM
    anywhere in the pod converges every rank onto one boundary.
    """

    def __init__(self, address, rank, world, hb_interval=None,
                 handler=None, consensus_timeout=None, barrier_timeout=None,
                 dead_timeout=None, block=True, margin=None):
        if isinstance(address, str):
            host, port = address.rsplit(":", 1)
            address = (host, int(port))
        self._addr = tuple(address)
        self.rank = int(rank)
        self.world = int(world)
        self._hb_interval = (_env_float("PADDLE_TPU_ELASTIC_HB_INTERVAL", 0.5)
                             if hb_interval is None else float(hb_interval))
        self._consensus_timeout = (
            _env_float("PADDLE_TPU_ELASTIC_CONSENSUS_TIMEOUT", 60.0)
            if consensus_timeout is None else float(consensus_timeout))
        self._barrier_timeout = (
            _env_float("PADDLE_TPU_ELASTIC_BARRIER_TIMEOUT", 120.0)
            if barrier_timeout is None else float(barrier_timeout))
        self._dead_timeout = (_env_float("PADDLE_TPU_ELASTIC_DEAD_TIMEOUT",
                                         10.0)
                              if dead_timeout is None else float(dead_timeout))
        self._handler = handler  # None -> process PreemptionHandler
        # block=True: stop at the boundary until consensus resolves —
        # correct ONLY for collective-free training (independent
        # replicas). Training with cross-process collectives MUST use
        # block=False: a rank parked at its boundary would wedge the
        # peers already inside the next step's collective, so instead
        # every rank keeps training, proposals are fire-and-forget, and
        # the coordinator pushes the agreed step `margin` boundaries
        # into the future (always still reachable: per-step sync bounds
        # the skew below margin).
        self._block = bool(block)
        self._margin = (_env_int("PADDLE_TPU_ELASTIC_MARGIN", 2)
                        if margin is None else int(margin))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._step = 0
        self._last_step_s = None
        self._fresh_step_s = False
        self._save_requested = False
        self._save_reason = None
        self._consensus = None
        self._stragglers = []
        self._dead = []
        self._fail_since = None
        self._coordinator = None  # rank 0 owns the server through us
        self._hb_thread = None

    # ------------------------------------------------------------- wiring
    def start(self):
        """Say hello (retrying through coordinator startup races) and
        start the heartbeat thread."""
        call_with_retry(self._rpc, {"type": "hello", "rank": self.rank},
                        retry_on=(OSError, ValueError),
                        max_attempts=20, base_delay=0.05, max_delay=0.5,
                        deadline=self._dead_timeout + 10.0)
        t = threading.Thread(target=self._hb_loop, name="elastic-heartbeat",
                             daemon=True)
        self._hb_thread = t
        t.start()
        return self

    def close(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        if self._coordinator is not None:
            self._coordinator.close()
        _clear_active(self)

    def _rpc(self, msg, timeout=5.0):
        with socket.create_connection(self._addr, timeout=timeout) as s:
            f = s.makefile("rwb")
            f.write(json.dumps(msg).encode("utf-8") + b"\n")
            f.flush()
            line = f.readline(1 << 20)
        if not line:
            raise ConnectionError("empty coordinator reply")
        return json.loads(line.decode("utf-8"))

    def _preempt_pending(self):
        h = self._handler
        if h is None:
            h = preemption.get_preemption_handler()
        return h.requested

    def _send_hb(self):
        """One heartbeat round-trip; folds the reply into local state.
        Returns the reply (or None on coordinator failure).

        A step duration is gossiped AT MOST ONCE: the background
        heartbeat re-sending the same sample between boundaries would
        multiply one slow step into straggler_n strikes (the coordinator
        counts strikes per sample, and the contract is per STEP)."""
        with self._lock:
            payload = {"type": "hb", "rank": self.rank, "step": self._step,
                       "step_s": (self._last_step_s
                                  if self._fresh_step_s else None)}
            self._fresh_step_s = False
        if self._preempt_pending():
            payload["preempt"] = True
        try:
            reply = self._rpc(payload)
        except (OSError, ValueError):
            now = time.monotonic()
            with self._lock:
                if self._fail_since is None:
                    self._fail_since = now
            return None
        self._absorb(reply)
        return reply

    def _absorb(self, reply):
        with self._lock:
            self._fail_since = None
            self._save_requested = bool(reply.get("save"))
            self._save_reason = reply.get("reason")
            if reply.get("consensus") is not None:
                self._consensus = int(reply["consensus"])
            self._stragglers = list(reply.get("stragglers", []))
            self._dead = list(reply.get("dead", []))

    def _hb_loop(self):
        while not self._stop.wait(self._hb_interval):
            self._send_hb()

    def _coordinator_lost(self):
        with self._lock:
            since = self._fail_since
        return since is not None and (time.monotonic() - since
                                      > self._dead_timeout)

    # ----------------------------------------------------- training-loop API
    def note_step(self, step, seconds=None):
        """Record a completed useful step: feeds the goodput ledger and
        stages (step, duration) for the next gossip round —
        :meth:`check_boundary` sends it inline at the boundary, so
        straggler math sees every step even with a slow heartbeat
        interval."""
        if seconds is not None:
            _goodput.account("step", seconds)
        with self._lock:
            self._step = max(self._step, int(step))
            self._last_step_s = (None if seconds is None
                                 else float(seconds))
            self._fresh_step_s = seconds is not None

    def request_save(self, reason=None):
        """Programmatic consensus trigger (tests, a cluster agent
        polling a maintenance-event API)."""
        try:
            reply = self._rpc({"type": "request_save", "rank": self.rank,
                               "reason": reason})
        except (OSError, ValueError):
            return
        self._absorb(reply)

    def check_boundary(self, completed_step):
        """Called at every step boundary with the just-completed step.

        Returns None (keep training) or the consensus step C: train
        until ``completed_step >= C``, save C, call :meth:`saved`, then
        exit 143. Blocks (bounded) while consensus resolves. Raises
        :class:`CoordinatorLost` / :class:`ConsensusTimeout` when the
        protocol cannot complete — exit 143 WITHOUT saving then."""
        # one fresh gossip round per boundary: carries this step's
        # duration (straggler math) + the local preemption flag, and
        # pulls the pod's save/consensus state — never act on a stale
        # heartbeat-thread snapshot
        self._send_hb()
        with self._lock:
            requested = self._save_requested
            consensus = self._consensus
        if not requested:
            if self._coordinator_lost():
                raise CoordinatorLost(
                    "coordinator unreachable at step boundary")
            return None
        if consensus is not None:
            return consensus
        propose = {"type": "propose", "rank": self.rank,
                   "step": int(completed_step),
                   "margin": 0 if self._block else self._margin}
        if not self._block:
            # collective mode: propose and KEEP TRAINING; the consensus
            # step (max + margin) lies ahead, and the next boundary
            # check collects it
            try:
                reply = self._rpc(propose)
            except (OSError, ValueError):
                if self._coordinator_lost():
                    raise CoordinatorLost(
                        "coordinator unreachable during consensus")
                now = time.monotonic()
                with self._lock:
                    if self._fail_since is None:
                        self._fail_since = now
                return None
            self._absorb(reply)
            if reply.get("consensus") is not None:
                return int(reply["consensus"])
            return None
        deadline = time.monotonic() + self._consensus_timeout
        while time.monotonic() < deadline:
            try:
                reply = self._rpc(propose)
            except (OSError, ValueError):
                if self._coordinator_lost():
                    raise CoordinatorLost(
                        "coordinator unreachable during consensus; "
                        "exiting without a (torn) solo save")
                now = time.monotonic()
                with self._lock:
                    if self._fail_since is None:
                        self._fail_since = now
                time.sleep(min(0.2, self._hb_interval))
                continue
            self._absorb(reply)
            if reply.get("consensus") is not None:
                return int(reply["consensus"])
            time.sleep(0.02)
        raise ConsensusTimeout(
            f"no consensus within {self._consensus_timeout:.0f}s "
            f"(proposed step {completed_step})")

    def finish_and_drain(self, final_step, timeout=None):
        """Announce completion and wait for the rest of the pod.

        Keeps rank 0's coordinator alive until every ALIVE rank is done
        — a straggler must not lose its coordinator because the fast
        ranks finished — and keeps this rank responsive to a late
        consensus (another host dies while we drain): returns None on a
        clean pod-wide finish, or the consensus step to save at (always
        our own final step, since a finished rank holds the max
        proposal). Coordinator loss during the drain means rank 0
        finished and exited: treated as done."""
        timeout = self._barrier_timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                reply = self._rpc({"type": "finished", "rank": self.rank,
                                   "step": int(final_step)})
            except (OSError, ValueError):
                if self._coordinator_lost():
                    return None
                now = time.monotonic()
                with self._lock:
                    if self._fail_since is None:
                        self._fail_since = now
                time.sleep(min(0.2, self._hb_interval))
                continue
            self._absorb(reply)
            if reply.get("save") and reply.get("consensus") is not None:
                return int(reply["consensus"])
            if reply.get("done"):
                return None
            time.sleep(min(0.2, self._hb_interval))
        return None  # drained our patience; the launcher owns the rest

    def barrier(self, name, timeout=None):
        """All-alive-ranks barrier through the coordinator (used by the
        multi-process checkpoint staging: dead ranks are excluded, so a
        host loss cannot hang the publish)."""
        timeout = self._barrier_timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        msg = {"type": "barrier", "rank": self.rank, "name": name}
        while time.monotonic() < deadline:
            try:
                reply = self._rpc(msg)
            except (OSError, ValueError):
                if self._coordinator_lost():
                    raise CoordinatorLost(
                        f"coordinator unreachable in barrier {name!r}")
                time.sleep(0.05)
                continue
            if reply.get("done"):
                return
            msg = {"type": "barrier_status", "rank": self.rank,
                   "name": name}
            time.sleep(0.02)
        raise TimeoutError(f"elastic barrier {name!r} timed out "
                           f"after {timeout:.0f}s")

    def saved(self, step):
        try:
            self._rpc({"type": "saved", "rank": self.rank,
                       "step": int(step)})
        except (OSError, ValueError):
            pass  # informational; the barrier already synchronised us

    def status(self):
        return self._rpc({"type": "status", "rank": self.rank})

    @property
    def stragglers(self):
        with self._lock:
            return list(self._stragglers)


class LocalElastic:
    """Single-host fallback with the same surface: consensus degrades
    to PR 2's save-at-next-boundary, barriers are no-ops."""

    rank = 0
    world = 1

    def __init__(self, handler=None):
        self._handler = handler

    def start(self):
        return self

    def close(self):
        _clear_active(self)

    def note_step(self, step, seconds=None):
        if seconds is not None:
            _goodput.account("step", seconds)

    def _requested(self):
        h = self._handler
        if h is None:
            h = preemption.get_preemption_handler()
        return h.requested

    def request_save(self, reason=None):
        h = self._handler
        if h is None:
            h = preemption.get_preemption_handler()
        h.request()

    def check_boundary(self, completed_step):
        return int(completed_step) if self._requested() else None

    def finish_and_drain(self, final_step, timeout=None):
        return int(final_step) if self._requested() else None

    def barrier(self, name, timeout=None):
        return None

    def saved(self, step):
        pass

    def status(self):
        return {"save": self._requested(), "consensus": None,
                "dead": [], "stragglers": [], "ranks": {}}

    @property
    def stragglers(self):
        return []


_active = None
_active_lock = threading.Lock()


def _clear_active(client):
    global _active
    with _active_lock:
        if _active is client:
            _active = None


def active_client():
    """The pod's elastic client, if init_from_env created one (the
    sharded checkpoint manager uses its barrier by default)."""
    with _active_lock:
        return _active


def init_from_env(handler=None, **kwargs):
    """Build the pod's elastic handle from the PADDLE_* env contract.

    Rank 0 starts the coordinator on PADDLE_TPU_ELASTIC_COORD (the
    launcher picks the address per attempt); every rank connects as a
    client. With world <= 1 or no coordinator address, returns the
    :class:`LocalElastic` fallback.
    """
    global _active
    try:
        world = int(os.environ.get("PADDLE_TRAINERS_NUM") or 1)
    except ValueError:
        world = 1
    addr = os.environ.get(ENV_COORD)
    rank = int(os.environ.get("PADDLE_TRAINER_ID") or 0)
    if world <= 1 or not addr:
        client = LocalElastic(handler=handler)
        with _active_lock:
            _active = client
        return client
    host, port = addr.rsplit(":", 1)
    coordinator = None
    if rank == 0:
        coordinator = ElasticCoordinator(world, host=host, port=int(port))
    client = ElasticClient((host, int(port)), rank, world, handler=handler,
                           **kwargs)
    client._coordinator = coordinator
    client.start()
    with _active_lock:
        _active = client
    return client
