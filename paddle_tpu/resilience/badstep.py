"""Bad-step guard: never let a NaN/Inf step poison the parameters.

One non-finite loss or gradient silently corrupts every parameter it
touches, and the run only "fails" thousands of steps later when someone
looks at the loss curve. Defense is layered:

1. in-graph (:func:`guard_step`, or ``build_train_step(...,
   bad_step_guard=True)`` which fuses the same selection inside the
   compiled step): detect non-finite loss/updates and keep the previous
   params/opt_state — the step is skipped at zero host cost;
2. host-side (:class:`BadStepMonitor`): count *consecutive* bad steps;
   past a threshold skipping is no longer enough (the state itself or
   the data stream is bad) — roll back to the last good checkpoint via
   a `resilience.checkpoint.CheckpointManager`.

This composes with `amp.GradScaler`: the scaler already skips updates
on overflow and re-scales; attach a monitor
(``scaler.attach_bad_step_monitor``) and its overflow skips feed the
same consecutive-bad-step accounting (see MIGRATION.md).
"""
import jax
import jax.numpy as jnp

OK = "ok"
SKIP = "skipped"
ROLLBACK = "rollback"


def tree_nonfinite(tree):
    """Scalar bool array: any non-finite value in any floating leaf."""
    bad = jnp.asarray(False)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.inexact):
            bad = bad | ~jnp.all(jnp.isfinite(leaf))
    return bad


def select_tree(bad, on_bad, on_good):
    """Per-leaf jnp.where(bad, on_bad, on_good) — the branchless skip
    that XLA compiles instead of a host round-trip."""
    return jax.tree_util.tree_map(
        lambda b, g: jnp.where(bad, b, g), on_bad, on_good)


def guard_step(step_fn):
    """Wrap a functional train step so bad steps become no-ops.

    step_fn(params, opt_state, *rest) -> (loss, new_params, new_opt).
    Returns guarded(params, opt_state, *rest) ->
    (loss, params', opt_state', bad) where bad is a scalar bool array
    and params'/opt_state' equal the INPUTS when bad.

    The wrapper is pure jnp, so ``jax.jit(guard_step(step))`` keeps the
    whole guard on-device. Do not apply it around an already-jitted
    step that donates its inputs — the guard needs the old state alive
    (use ``build_train_step(bad_step_guard=True)`` there, which selects
    before donation is visible).
    """

    def guarded(params, opt_state, *rest):
        loss, new_params, new_opt = step_fn(params, opt_state, *rest)
        bad = tree_nonfinite(loss) | tree_nonfinite(new_params)
        return (loss,
                select_tree(bad, params, new_params),
                select_tree(bad, opt_state, new_opt),
                bad)

    return guarded


class BadStepMonitor:
    """Consecutive-bad-step accounting + checkpoint rollback policy.

    record(bad) -> OK | SKIP | ROLLBACK. After `threshold` consecutive
    bad steps it returns ROLLBACK (and resets the streak); the caller
    restores state — via :meth:`restore` when a manager is attached,
    and `on_rollback` fires for custom recovery (reload data pipeline,
    lower LR, page an operator...).
    """

    def __init__(self, threshold=3, manager=None, on_rollback=None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.manager = manager
        self.on_rollback = on_rollback
        self.consecutive = 0
        self.total_bad = 0
        self.rollbacks = 0

    def record(self, bad):
        if not bool(bad):
            self.consecutive = 0
            return OK
        self.total_bad += 1
        self.consecutive += 1
        if self.consecutive >= self.threshold:
            self.consecutive = 0
            self.rollbacks += 1
            if self.on_rollback is not None:
                self.on_rollback()
            return ROLLBACK
        return SKIP

    def restore(self):
        """-> (state, step) from the attached manager's last good
        checkpoint (verified, with fallback)."""
        if self.manager is None:
            raise RuntimeError("BadStepMonitor has no CheckpointManager "
                               "attached; pass manager= to restore")
        state, step = self.manager.load()
        if state is None:
            raise RuntimeError(
                f"rollback requested but no usable checkpoint under "
                f"{self.manager.root}")
        return state, step

    def state_dict(self):
        return {"consecutive": self.consecutive, "total_bad": self.total_bad,
                "rollbacks": self.rollbacks}
