"""Bad-step guard: never let a NaN/Inf step poison the parameters.

One non-finite loss or gradient silently corrupts every parameter it
touches, and the run only "fails" thousands of steps later when someone
looks at the loss curve. Defense is layered:

1. in-graph (:func:`guard_step`, or ``build_train_step(...,
   bad_step_guard=True)`` which fuses the same selection inside the
   compiled step): detect non-finite loss/updates and keep the previous
   params/opt_state — the step is skipped at zero host cost;
2. host-side (:class:`BadStepMonitor`): count *consecutive* bad steps;
   past a threshold skipping is no longer enough (the state itself or
   the data stream is bad) — roll back to the last good checkpoint via
   a `resilience.checkpoint.CheckpointManager`.

This composes with `amp.GradScaler`: the scaler already skips updates
on overflow and re-scales; attach a monitor
(``scaler.attach_bad_step_monitor``) and its overflow skips feed the
same consecutive-bad-step accounting (see MIGRATION.md).
"""
import time

import jax
import jax.numpy as jnp

from ..obs import goodput as _goodput
from ..obs import metrics as _obs

OK = "ok"
SKIP = "skipped"
ROLLBACK = "rollback"

_BAD_STEPS = _obs.counter("paddle_badstep_bad_total",
                          "Non-finite (skipped) training steps")
_ROLLBACKS = _obs.counter(
    "paddle_badstep_rollbacks_total",
    "Checkpoint rollbacks after consecutive bad steps")


def tree_nonfinite(tree):
    """Scalar bool array: any non-finite value in any floating leaf."""
    bad = jnp.asarray(False)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.inexact):
            bad = bad | ~jnp.all(jnp.isfinite(leaf))
    return bad


def select_tree(bad, on_bad, on_good):
    """Per-leaf jnp.where(bad, on_bad, on_good) — the branchless skip
    that XLA compiles instead of a host round-trip."""
    return jax.tree_util.tree_map(
        lambda b, g: jnp.where(bad, b, g), on_bad, on_good)


def guard_step(step_fn):
    """Wrap a functional train step so bad steps become no-ops.

    step_fn(params, opt_state, *rest) -> (loss, new_params, new_opt).
    Returns guarded(params, opt_state, *rest) ->
    (loss, params', opt_state', bad) where bad is a scalar bool array
    and params'/opt_state' equal the INPUTS when bad.

    The wrapper is pure jnp, so ``jax.jit(guard_step(step))`` keeps the
    whole guard on-device. Do not apply it around an already-jitted
    step that donates its inputs — the guard needs the old state alive
    (use ``build_train_step(bad_step_guard=True)`` there, which selects
    before donation is visible).
    """

    def guarded(params, opt_state, *rest):
        loss, new_params, new_opt = step_fn(params, opt_state, *rest)
        bad = tree_nonfinite(loss) | tree_nonfinite(new_params)
        return (loss,
                select_tree(bad, params, new_params),
                select_tree(bad, opt_state, new_opt),
                bad)

    return guarded


class BadStepMonitor:
    """Consecutive-bad-step accounting + checkpoint rollback policy.

    record(bad) -> OK | SKIP | ROLLBACK. After `threshold` consecutive
    bad steps it returns ROLLBACK (and resets the streak); the caller
    restores state — via :meth:`restore` when a manager is attached,
    and `on_rollback` fires for custom recovery (reload data pipeline,
    lower LR, page an operator...).
    """

    def __init__(self, threshold=3, manager=None, on_rollback=None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.manager = manager
        self.on_rollback = on_rollback
        self.consecutive = 0
        self.total_bad = 0
        self.rollbacks = 0

    def record(self, bad):
        if not bool(bad):
            self.consecutive = 0
            return OK
        self.total_bad += 1
        self.consecutive += 1
        _BAD_STEPS.inc()
        if self.consecutive >= self.threshold:
            self.consecutive = 0
            self.rollbacks += 1
            _ROLLBACKS.inc()
            if self.on_rollback is not None:
                self.on_rollback()
            return ROLLBACK
        return SKIP

    def restore(self):
        """-> (state, step) from the attached manager's last good
        checkpoint (verified, with fallback)."""
        if self.manager is None:
            raise RuntimeError("BadStepMonitor has no CheckpointManager "
                               "attached; pass manager= to restore")
        t0 = time.perf_counter()
        state, step = self.manager.load()
        if state is None:
            raise RuntimeError(
                f"rollback requested but no usable checkpoint under "
                f"{self.manager.root}")
        # restore time is goodput lost to the rollback, not to the
        # checkpoint category (the load span already records itself)
        _goodput.account("rollback", time.perf_counter() - t0)
        return state, step

    def state_dict(self):
        return {"consecutive": self.consecutive, "total_bad": self.total_bad,
                "rollbacks": self.rollbacks}
