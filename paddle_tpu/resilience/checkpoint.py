"""Atomic, self-verifying checkpoints.

A checkpoint either exists completely or not at all: all files are
written into a hidden temp directory, fsynced, then published with one
``os.replace`` — a crash at any point leaves the previous checkpoint
untouched (the temp dir is garbage-collected on the next save). Each
checkpoint carries a ``MANIFEST.json`` with the step number and
per-file SHA-256 digests (plus per-leaf array checksums for the default
pickle payload), and loads verify the manifest before trusting the
payload, falling back to the previous good checkpoint on corruption.

On-disk layout (documented in README "Resilience"):

    <root>/
      LATEST                # text: name of the newest published ckpt
      ckpt-<step>/
        MANIFEST.json       # {"format":1,"step":N,"ts":...,"files":{...},
                            #  "leaves":{...}}
        state.pdparams      # default payload (framework.save pickle)
      .tmp-ckpt-<step>-<pid>/   # in-flight save; never read

The payload is pluggable (``writer``/``reader``) so the same manager
fronts the orbax/TensorStore sharded path
(`distributed.checkpoint.sharded_checkpoint_manager`) and the plain
pickle path. Single-writer-per-root is assumed (one trainer process
saves; any number may read).
"""
import hashlib
import json
import os
import shutil
import time
import warnings

import numpy as np

from ..obs import goodput as _goodput
from ..obs import metrics as _obs
from ..obs import tracing as _tracing
from . import chaos
from .retry import call_with_retry

MANIFEST_NAME = "MANIFEST.json"
LATEST_NAME = "LATEST"
FORMAT_VERSION = 1

# Registry-backed checkpoint telemetry: save/load durations feed the
# goodput accountant (checkpoint time is goodput the fleet loses) and
# the Prometheus exposition.
_SAVES = _obs.counter("paddle_checkpoint_saves_total",
                      "Published checkpoints")
_SAVE_SECONDS = _obs.histogram(
    "paddle_checkpoint_save_seconds", "Checkpoint publish duration",
    buckets=_obs.log_buckets(0.001, 4.0, 10))
_LOADS = _obs.counter("paddle_checkpoint_loads_total",
                      "Verified checkpoint loads")
_FALLBACKS = _obs.counter(
    "paddle_checkpoint_fallbacks_total",
    "Corrupt/unusable checkpoints skipped during load")


class CheckpointCorrupt(RuntimeError):
    """Manifest missing/unreadable or a payload file fails verification."""


# --------------------------------------------------------------- primitives

def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path, data):
    """Write bytes so readers see the old content or the new, never a
    truncated mix (tmp in the same dir + fsync + os.replace)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(d)
    return path


def atomic_write_json(path, obj):
    return atomic_write_bytes(
        path, json.dumps(obj, sort_keys=True).encode("utf-8"))


def file_sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def _leaf_bytes(leaf):
    v = getattr(leaf, "_value", leaf)  # Tensor -> backing array
    try:
        arr = np.asarray(v)
    except Exception:  # noqa: BLE001 — opaque leaf, hash its repr
        return repr(v).encode("utf-8"), "opaque", ()
    return np.ascontiguousarray(arr).tobytes(), str(arr.dtype), arr.shape


def flatten_tree(state, prefix=""):
    """Flatten a nested dict/list/tuple pytree into an ordered
    {dotted.path: leaf} map — the ONE leaf-naming walker shared by the
    per-leaf checksum forensics here and the host-shard index
    (distributed.checkpoint), which must name leaves identically."""
    out = {}
    if isinstance(state, dict):
        for k, v in state.items():
            out.update(flatten_tree(v, f"{prefix}{k}."))
    elif isinstance(state, (list, tuple)):
        for i, v in enumerate(state):
            out.update(flatten_tree(v, f"{prefix}{i}."))
    else:
        out[prefix.rstrip(".") or "<root>"] = state
    return out


def leaf_checksums(state, prefix=""):
    """{dotted.path: {sha256, dtype, shape}} over flatten_tree —
    corruption diagnostics name the exact tensor, not just "the
    file"."""
    out = {}
    for path, leaf in flatten_tree(state, prefix).items():
        data, dtype, shape = _leaf_bytes(leaf)
        out[path] = {"sha256": hashlib.sha256(data).hexdigest(),
                     "dtype": dtype, "shape": list(shape)}
    return out


def _default_writer(state, ckpt_dir, leaf_manifest=False):
    from .. import framework

    framework.save(state, os.path.join(ckpt_dir, "state.pdparams"))
    # leaf hashing walks a full copy of every tensor — integrity is
    # already guaranteed by the per-file sha256, so per-leaf forensics
    # (naming the exact corrupted tensor) are opt-in
    return leaf_checksums(state) if leaf_manifest else None


def _default_reader(ckpt_dir):
    from .. import framework

    return framework.load(os.path.join(ckpt_dir, "state.pdparams"))


# ------------------------------------------------------------------ manager

class CheckpointManager:
    """Atomic save / verified load / retention GC over one directory.

    keep: retention — newest N published checkpoints survive GC (the
    one LATEST names is always kept).
    writer(state, dir) -> leaves|None: materialize the payload into dir.
    reader(dir) -> state: load the payload back.
    io_retries: transient OSErrors during the payload write are retried
    with backoff before the save is abandoned.
    leaf_manifest: also record per-leaf array checksums in the manifest
    (default writer only) — corruption reports then name the exact
    tensor, at the cost of hashing every leaf a second time on save.
    """

    def __init__(self, root, keep=3, prefix="ckpt", writer=None, reader=None,
                 io_retries=3, leaf_manifest=False):
        self.root = os.path.abspath(root)
        self.keep = keep
        self.prefix = prefix
        if writer is None:
            def writer(state, d):
                return _default_writer(state, d, leaf_manifest)
        self._writer = writer
        self._reader = reader or _default_reader
        self._io_retries = io_retries

    # -------------------------------------------------------------- naming
    def _name(self, step):
        return f"{self.prefix}-{step}"

    def _step_of(self, name):
        tag = f"{self.prefix}-"
        if not name.startswith(tag):
            return None
        try:
            return int(name[len(tag):])
        except ValueError:
            return None

    def all_steps(self):
        """Published checkpoint steps, ascending."""
        if not os.path.isdir(self.root):
            return []
        steps = [s for n in os.listdir(self.root)
                 if (s := self._step_of(n)) is not None
                 and os.path.isdir(os.path.join(self.root, n))]
        return sorted(steps)

    def path(self, step):
        return os.path.join(self.root, self._name(step))

    def latest_name(self):
        try:
            with open(os.path.join(self.root, LATEST_NAME)) as f:
                name = f.read().strip()
            return name or None
        except OSError:
            return None

    def latest_step(self):
        name = self.latest_name()
        if name is not None:
            step = self._step_of(name)
            if step is not None and os.path.isdir(
                    os.path.join(self.root, name)):
                return step
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ---------------------------------------------------------------- save
    def save(self, state, step, extra=None):
        """Publish `state` as checkpoint `step`. Returns the final path.

        Crash-safe at every point: the payload + manifest land in a temp
        dir, one os.replace publishes, then LATEST flips (also
        atomically). Transient write errors retry with backoff."""
        t_save = time.perf_counter()
        os.makedirs(self.root, exist_ok=True)
        name = self._name(step)
        final = os.path.join(self.root, name)
        tmp = os.path.join(self.root, f".tmp-{name}-{os.getpid()}")

        def _write_payload():
            if os.path.isdir(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            chaos.hit("checkpoint.write")
            leaves = self._writer(state, tmp)
            manifest = {"format": FORMAT_VERSION, "step": int(step),
                        "ts": time.time(), "files": {}}
            if leaves:
                manifest["leaves"] = leaves
            if extra:
                manifest["extra"] = extra
            for dirpath, _, files in os.walk(tmp):
                for fn in files:
                    full = os.path.join(dirpath, fn)
                    rel = os.path.relpath(full, tmp)
                    manifest["files"][rel] = {
                        "sha256": file_sha256(full),
                        "size": os.path.getsize(full)}
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())

        try:
            call_with_retry(_write_payload, retry_on=(OSError,),
                            max_attempts=self._io_retries, base_delay=0.05)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        chaos.hit("checkpoint.rename")
        old = None
        if os.path.isdir(final):  # re-save of the same step: move the
            # previous copy aside atomically, never delete-then-publish
            old = os.path.join(self.root, f".old-{name}-{os.getpid()}")
            if os.path.isdir(old):
                shutil.rmtree(old, ignore_errors=True)
            os.replace(final, old)
        try:
            os.replace(tmp, final)
        except BaseException:
            if old is not None and not os.path.isdir(final):
                os.replace(old, final)  # publish failed: restore it
            raise
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        _fsync_dir(self.root)
        chaos.hit("checkpoint.latest")
        atomic_write_bytes(os.path.join(self.root, LATEST_NAME),
                           name.encode("utf-8"))
        self.gc()
        dt = time.perf_counter() - t_save
        _SAVES.inc()
        _SAVE_SECONDS.observe(dt)
        _goodput.account("checkpoint", dt)
        _tracing.record_span("checkpoint.save", dt, step=int(step))
        return final

    # -------------------------------------------------------------- verify
    def verify(self, ckpt_dir):
        """Check every payload file against the manifest. Returns the
        manifest; raises CheckpointCorrupt on any mismatch."""
        mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorrupt(
                f"{ckpt_dir}: manifest unreadable: {e}") from e
        if manifest.get("format") != FORMAT_VERSION:
            raise CheckpointCorrupt(
                f"{ckpt_dir}: unknown manifest format "
                f"{manifest.get('format')!r}")
        for rel, meta in manifest.get("files", {}).items():
            full = os.path.join(ckpt_dir, rel)
            if not os.path.isfile(full):
                raise CheckpointCorrupt(f"{ckpt_dir}: missing file {rel}")
            if os.path.getsize(full) != meta["size"]:
                raise CheckpointCorrupt(
                    f"{ckpt_dir}: size mismatch for {rel}")
            if file_sha256(full) != meta["sha256"]:
                raise CheckpointCorrupt(
                    f"{ckpt_dir}: checksum mismatch for {rel}")
        return manifest

    # ---------------------------------------------------------------- load
    def _candidates(self):
        names = []
        latest = self.latest_name()
        if latest is not None:
            names.append(latest)
        for step in reversed(self.all_steps()):
            n = self._name(step)
            if n not in names:
                names.append(n)
        return names

    def load(self, verify=True):
        """-> (state, step) from the newest checkpoint that verifies,
        falling back through older ones; (None, -1) when none usable."""
        t_load = time.perf_counter()
        for name in self._candidates():
            ckpt_dir = os.path.join(self.root, name)
            if not os.path.isdir(ckpt_dir):
                continue
            try:
                manifest = self.verify(ckpt_dir) if verify else None
                state = self._reader(ckpt_dir)
                if manifest is None:
                    step = self._step_of(name)
                    step = -1 if step is None else step
                else:
                    step = int(manifest["step"])
                _LOADS.inc()
                _tracing.record_span("checkpoint.load",
                                     time.perf_counter() - t_load,
                                     step=step)
                return state, step
            except Exception as e:  # noqa: BLE001 — fall back past corruption
                _FALLBACKS.inc()
                warnings.warn(
                    f"checkpoint {ckpt_dir} unusable ({e}); "
                    f"falling back to an older checkpoint")
        return None, -1

    # ------------------------------------------------------------------ gc
    def gc(self):
        """Drop all but the newest `keep` checkpoints and stale temp
        dirs. The checkpoint LATEST names is never dropped."""
        if not os.path.isdir(self.root):
            return
        steps = self.all_steps()
        latest = self.latest_name()
        if self.keep and self.keep > 0:
            for step in steps[:-self.keep]:
                name = self._name(step)
                if name == latest:
                    continue
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        for n in os.listdir(self.root):
            if (n.startswith(".tmp-") and
                    n != f".tmp-{latest}-{os.getpid()}") or \
                    n.startswith(".old-"):
                full = os.path.join(self.root, n)
                # a crashed writer's leftovers; current-process saves
                # clean their own tmp before writing
                if os.path.isdir(full):
                    shutil.rmtree(full, ignore_errors=True)
