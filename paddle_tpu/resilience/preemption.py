"""Preemption handling: save-and-exit at the next safe boundary.

Preemptible TPU/GPU clusters deliver SIGTERM (maintenance events,
spot reclaim, job-queue eviction) with a grace window. Killing a
trainer mid-step loses the epoch; the right move is: note the request
in the signal handler (async-signal-safe — just an Event), finish the
current step/epoch, checkpoint, write a resumable marker, and exit with
the conventional 128+SIGTERM status so the scheduler reschedules.

Wired into `incubate.checkpoint.TrainEpochRange` and `hapi.Model.fit`;
tests inject the signal with `resilience.chaos` (signum=SIGTERM).
"""
import json
import os
import signal
import threading

from ..obs import metrics as _obs

MARKER_NAME = "PREEMPTED.json"

_PREEMPTION_SAVES = _obs.counter(
    "paddle_preemption_saves_total",
    "Preemption save-and-exit markers written (resumable shutdowns)")
EXIT_CODE = 143  # 128 + SIGTERM — what a scheduler expects from a
                 # gracefully preempted worker


class PreemptedExit(SystemExit):
    """Raised at a step/epoch boundary after the preemption checkpoint
    is on disk; carries the conventional exit status."""

    def __init__(self, step=None):
        super().__init__(EXIT_CODE)
        self.step = step


def _chainable(prev):
    """Is a pre-existing handler worth chaining? Only a real callable
    the application installed — the stock dispositions (SIG_DFL,
    SIG_IGN, Python's default KeyboardInterrupt raiser) are what this
    handler deliberately replaces."""
    return (callable(prev) and prev not in (signal.SIG_DFL, signal.SIG_IGN)
            and prev is not signal.default_int_handler)


class PreemptionHandler:
    """Signal handler that records a preemption request.

    The handler only sets a flag (async-signal-safe); training loops
    poll `requested` at boundaries and perform the save/exit themselves.
    install() is idempotent, and a pre-existing NON-DEFAULT handler is
    chained (called after the flag is set) rather than silently
    overwritten — a cluster agent's own SIGTERM bookkeeping keeps
    running. uninstall() restores the previous handlers.
    """

    def __init__(self):
        self._requested = threading.Event()
        self._prev = {}
        self._installed = False
        self.signum = None  # which signal fired (telemetry)

    # tpu-resource: acquires=signal_handler
    def install(self, signals=(signal.SIGTERM, signal.SIGINT)):
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal only works on the main thread
        for s in signals:
            if s in self._prev:
                continue  # idempotent per signal
            try:
                self._prev[s] = signal.signal(s, self._on_signal)
            except (ValueError, OSError):  # non-main thread / exotic env
                pass
        self._installed = True
        return self

    # tpu-resource: releases=signal_handler
    def uninstall(self):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev = {}
        self._installed = False

    def _on_signal(self, signum, frame):
        self.signum = signum
        self._requested.set()
        prev = self._prev.get(signum)
        if _chainable(prev) and prev is not self._on_signal:
            prev(signum, frame)

    @property
    def requested(self):
        return self._requested.is_set()

    def request(self):
        """Programmatic preemption (tests, cluster agents polling a
        maintenance-event API instead of a signal)."""
        self._requested.set()

    def clear(self):
        self._requested.clear()
        self.signum = None


_handler = None
_handler_lock = threading.Lock()


def get_preemption_handler():
    global _handler
    with _handler_lock:
        if _handler is None:
            _handler = PreemptionHandler()
        return _handler


# tpu-resource: acquires=signal_handler
def install(signals=(signal.SIGTERM, signal.SIGINT)):
    return get_preemption_handler().install(signals)


def preemption_requested():
    return _handler is not None and _handler.requested


# ----------------------------------------------------------------- markers

def write_resume_marker(save_dir, step=None, extra=None, world_size=None):
    """Atomically record "this run was preempted after saving at
    `step`" so the restart knows the checkpoint is resumable (and
    schedulers/tooling can distinguish preemption from a crash).
    world_size (default: the PADDLE_TRAINERS_NUM env, when set) lets
    the restart detect a marker written by a different slice shape."""
    from .checkpoint import atomic_write_json

    if world_size is None:
        try:
            world_size = int(os.environ.get("PADDLE_TRAINERS_NUM") or 0)
        except ValueError:
            world_size = 0
        world_size = world_size or None
    payload = {"preempted": True, "step": step}
    if world_size is not None:
        payload["world_size"] = int(world_size)
    if extra:
        payload.update(extra)
    os.makedirs(save_dir, exist_ok=True)
    path = atomic_write_json(os.path.join(save_dir, MARKER_NAME), payload)
    _PREEMPTION_SAVES.inc()
    return path


def read_resume_marker(save_dir):
    try:
        with open(os.path.join(save_dir, MARKER_NAME)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear_resume_marker(save_dir):
    try:
        os.remove(os.path.join(save_dir, MARKER_NAME))
    except OSError:
        pass


def resolve_resume_step(save_dir, available_step=None, world_size=None):
    """Reconcile the resume marker against what is actually on disk.

    The marker is a HINT, not the source of truth — the verified
    checkpoint store is. Edge cases this resolves (all warn rather than
    crash, because a restart must always make progress):

    - marker present but the checkpoint it names is missing/corrupt:
      resume from ``available_step`` (the newest step the store could
      verify — CheckpointManager.load's fallback result);
    - marker step ahead of the store's LATEST (the marker write raced a
      crash after an unpublished save): clamp to ``available_step``;
    - marker written by a different world size: trust the step (the
      sharded store reshards on load) but surface the mismatch so
      non-reshardable callers can start clean instead.

    Returns ``(step, info)``: ``step`` is the boundary to resume from
    (``available_step`` when the marker is unusable, ``None`` when
    neither exists), ``info`` carries ``marker``, ``stale_world`` and
    ``clamped`` flags for the caller's logging.
    """
    import warnings

    marker = read_resume_marker(save_dir)
    info = {"marker": marker, "stale_world": False, "clamped": False}
    if marker is None:
        return available_step, info
    mstep = marker.get("step")
    mworld = marker.get("world_size")
    if (world_size is not None and mworld is not None
            and int(mworld) != int(world_size)):
        info["stale_world"] = True
        warnings.warn(
            f"resume marker in {save_dir} was written by world_size="
            f"{mworld}, resuming with world_size={world_size}: valid only "
            "if the checkpoint store reshards on load")
    if mstep is None:
        return available_step, info
    if available_step is None:
        info["clamped"] = True
        warnings.warn(
            f"resume marker names step {mstep} but no usable checkpoint "
            f"exists in {save_dir}; starting clean")
        return None, info
    if int(mstep) > int(available_step):
        info["clamped"] = True
        warnings.warn(
            f"resume marker names step {mstep} but the newest verified "
            f"checkpoint is step {available_step} (marker ahead of "
            "LATEST, or the checkpoint it names was lost); resuming from "
            f"{available_step}")
        return available_step, info
    return int(mstep), info
