"""Preemption handling: save-and-exit at the next safe boundary.

Preemptible TPU/GPU clusters deliver SIGTERM (maintenance events,
spot reclaim, job-queue eviction) with a grace window. Killing a
trainer mid-step loses the epoch; the right move is: note the request
in the signal handler (async-signal-safe — just an Event), finish the
current step/epoch, checkpoint, write a resumable marker, and exit with
the conventional 128+SIGTERM status so the scheduler reschedules.

Wired into `incubate.checkpoint.TrainEpochRange` and `hapi.Model.fit`;
tests inject the signal with `resilience.chaos` (signum=SIGTERM).
"""
import json
import os
import signal
import threading

from ..obs import metrics as _obs

MARKER_NAME = "PREEMPTED.json"

_PREEMPTION_SAVES = _obs.counter(
    "paddle_preemption_saves_total",
    "Preemption save-and-exit markers written (resumable shutdowns)")
EXIT_CODE = 143  # 128 + SIGTERM — what a scheduler expects from a
                 # gracefully preempted worker


class PreemptedExit(SystemExit):
    """Raised at a step/epoch boundary after the preemption checkpoint
    is on disk; carries the conventional exit status."""

    def __init__(self, step=None):
        super().__init__(EXIT_CODE)
        self.step = step


class PreemptionHandler:
    """Signal handler that records a preemption request.

    The handler only sets a flag (async-signal-safe); training loops
    poll `requested` at boundaries and perform the save/exit themselves.
    install() is idempotent and chains nothing — uninstall() restores
    the previous handlers.
    """

    def __init__(self):
        self._requested = threading.Event()
        self._prev = {}
        self._installed = False
        self.signum = None  # which signal fired (telemetry)

    def install(self, signals=(signal.SIGTERM, signal.SIGINT)):
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal only works on the main thread
        for s in signals:
            if s in self._prev:
                continue  # idempotent per signal
            try:
                self._prev[s] = signal.signal(s, self._on_signal)
            except (ValueError, OSError):  # non-main thread / exotic env
                pass
        self._installed = True
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev = {}
        self._installed = False

    def _on_signal(self, signum, frame):
        self.signum = signum
        self._requested.set()

    @property
    def requested(self):
        return self._requested.is_set()

    def request(self):
        """Programmatic preemption (tests, cluster agents polling a
        maintenance-event API instead of a signal)."""
        self._requested.set()

    def clear(self):
        self._requested.clear()
        self.signum = None


_handler = None
_handler_lock = threading.Lock()


def get_preemption_handler():
    global _handler
    with _handler_lock:
        if _handler is None:
            _handler = PreemptionHandler()
        return _handler


def install(signals=(signal.SIGTERM, signal.SIGINT)):
    return get_preemption_handler().install(signals)


def preemption_requested():
    return _handler is not None and _handler.requested


# ----------------------------------------------------------------- markers

def write_resume_marker(save_dir, step=None, extra=None):
    """Atomically record "this run was preempted after saving at
    `step`" so the restart knows the checkpoint is resumable (and
    schedulers/tooling can distinguish preemption from a crash)."""
    from .checkpoint import atomic_write_json

    payload = {"preempted": True, "step": step}
    if extra:
        payload.update(extra)
    os.makedirs(save_dir, exist_ok=True)
    path = atomic_write_json(os.path.join(save_dir, MARKER_NAME), payload)
    _PREEMPTION_SAVES.inc()
    return path


def read_resume_marker(save_dir):
    try:
        with open(os.path.join(save_dir, MARKER_NAME)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear_resume_marker(save_dir):
    try:
        os.remove(os.path.join(save_dir, MARKER_NAME))
    except OSError:
        pass
