"""Deterministic chaos / fault-injection harness.

Production resilience code is only trustworthy if its failure paths run
in CI, so every fault the runtime defends against (checkpoint-write
crashes, preemption signals, NaN gradients, slow I/O) is injectable
here — *deterministically*, by visit count rather than randomness, so a
failing chaos test replays bit-for-bit.

Instrumented code declares named *sites* by calling :func:`hit` (or
:func:`poison` for data corruption). Tests arm faults against a site:

    from paddle_tpu.resilience import chaos
    with chaos.fault("checkpoint.write", exc=OSError("disk full"), at=2):
        ...   # the 2nd checkpoint write raises; 1st and 3rd succeed

Supported actions per fault: raise an exception, deliver a signal to
this process, sleep (delayed I/O), or NaN-poison an array. A fault
fires on visits ``at .. at+times-1`` of its site. When nothing is
armed, ``hit()`` is a near-free early return — safe on hot paths.
"""
import os
import threading
import time

import numpy as np


class Fault:
    """One armed fault: fires on visits ``at .. at+times-1`` of ``site``."""

    def __init__(self, site, at=1, times=1, exc=None, signum=None,
                 delay=0.0, nan=False):
        if at < 1:
            raise ValueError(f"at is 1-based, got {at}")
        self.site = site
        self.at = at
        self.times = times
        self.exc = exc
        self.signum = signum
        self.delay = delay
        self.nan = nan
        self.fired = 0

    def covers(self, visit):
        return self.at <= visit < self.at + self.times


class ChaosMonkey:
    """Process-global registry of armed faults and per-site visit counts."""

    def __init__(self):
        self._lock = threading.RLock()
        self._faults = []
        self._counts = {}
        self.log = []  # (site, visit, action) — for test assertions

    # ------------------------------------------------------------ arming
    def arm(self, site, at=1, times=1, exc=None, signum=None, delay=0.0,
            nan=False):
        f = Fault(site, at=at, times=times, exc=exc, signum=signum,
                  delay=delay, nan=nan)
        with self._lock:
            self._faults.append(f)
        return f

    def disarm(self, fault):
        with self._lock:
            if fault in self._faults:
                self._faults.remove(fault)

    def reset(self):
        with self._lock:
            self._faults = []
            self._counts = {}
            self.log = []

    def armed(self, site=None):
        with self._lock:
            if site is None:
                return bool(self._faults)
            return any(f.site == site for f in self._faults)

    def visits(self, site):
        with self._lock:
            return self._counts.get(site, 0)

    # ------------------------------------------------------------ firing
    def _visit(self, site):
        with self._lock:
            n = self._counts[site] = self._counts.get(site, 0) + 1
            matched = [f for f in self._faults
                       if f.site == site and f.covers(n)]
            for f in matched:
                f.fired += 1
        return n, matched

    def hit(self, site):
        """Record a visit to `site`; apply any armed fault covering it.

        Order per matched fault: delay, then signal, then raise — a
        single fault can model "slow write that then fails". Returns the
        visit number (1-based).
        """
        n, matched = self._visit(site)
        for f in matched:
            if f.delay:
                self.log.append((site, n, "delay"))
                time.sleep(f.delay)
            if f.signum is not None:
                self.log.append((site, n, "signal"))
                os.kill(os.getpid(), f.signum)
            if f.exc is not None:
                self.log.append((site, n, "raise"))
                raise f.exc() if isinstance(f.exc, type) else f.exc
        return n

    def poison(self, site, array):
        """Return `array`, NaN-poisoned when a ``nan=True`` fault covers
        this visit (how tests make "the gradients went NaN at step k"
        reproducible). Non-nan actions armed on the same site fire too."""
        n, matched = self._visit(site)
        poisoned = False
        for f in matched:
            if f.delay:
                self.log.append((site, n, "delay"))
                time.sleep(f.delay)
            if f.signum is not None:
                self.log.append((site, n, "signal"))
                os.kill(os.getpid(), f.signum)
            if f.exc is not None:
                self.log.append((site, n, "raise"))
                raise f.exc() if isinstance(f.exc, type) else f.exc
            if f.nan:
                poisoned = True
        if poisoned:
            self.log.append((site, n, "nan"))
            arr = np.array(array, dtype=np.asarray(array).dtype, copy=True)
            if not np.issubdtype(arr.dtype, np.floating):
                arr = arr.astype(np.float32)
            arr.fill(np.nan)
            return arr
        return array


monkey = ChaosMonkey()

# module-level aliases — instrumented code and tests use these
arm = monkey.arm
disarm = monkey.disarm
reset = monkey.reset
armed = monkey.armed
visits = monkey.visits
hit = monkey.hit
poison = monkey.poison


_EXC_WHITELIST = ("RuntimeError", "OSError", "IOError", "ValueError",
                  "TimeoutError", "ConnectionError")


def arm_from_env(env=None):
    """Arm faults described in the ``PADDLE_TPU_CHAOS`` env var — how a
    launcher (bench.py goodput, the elastic e2e suite) injects
    deterministic faults into SUBPROCESS trainers it cannot reach with
    ``chaos.arm`` directly.

    Spec: ``;``-separated faults, each ``,``-separated ``k=v`` pairs::

        PADDLE_TPU_CHAOS="site=train.step,signum=15,at=6,rank=1;site=io,exc=OSError"

    Keys: ``site`` (required), ``at``, ``times``, ``signum``, ``delay``,
    ``nan=1``, ``exc=<builtin exception name>``, and ``rank=<n>`` which
    arms the fault only when PADDLE_TRAINER_ID matches — one spec
    string fans out to a whole pod with per-rank targeting. Returns the
    list of armed Faults (empty when the var is unset)."""
    env = os.environ if env is None else env
    spec = env.get("PADDLE_TPU_CHAOS", "")
    my_rank = env.get("PADDLE_TRAINER_ID")
    armed = []
    for part in (p.strip() for p in spec.split(";")):
        if not part:
            continue
        kv = dict(item.split("=", 1) for item in part.split(","))
        if "site" not in kv:
            raise ValueError(f"PADDLE_TPU_CHAOS fault without site: {part!r}")
        if "rank" in kv and my_rank is not None \
                and int(kv["rank"]) != int(my_rank):
            continue
        kwargs = {"at": int(kv.get("at", 1)),
                  "times": int(kv.get("times", 1)),
                  "delay": float(kv.get("delay", 0.0)),
                  "nan": kv.get("nan") in ("1", "true")}
        if "signum" in kv:
            kwargs["signum"] = int(kv["signum"])
        if "exc" in kv:
            name = kv["exc"]
            if name not in _EXC_WHITELIST:
                raise ValueError(f"PADDLE_TPU_CHAOS exc {name!r} not in "
                                 f"{_EXC_WHITELIST}")
            import builtins

            kwargs["exc"] = getattr(builtins, name)
        armed.append(arm(kv["site"], **kwargs))
    return armed


class fault:
    """Context manager: arm a fault for the `with` body, disarm after.

    with chaos.fault("checkpoint.write", exc=OSError("boom")):
        ...
    """

    def __init__(self, site, **kwargs):
        self._args = (site, kwargs)
        self.fault = None

    def __enter__(self):
        site, kwargs = self._args
        self.fault = monkey.arm(site, **kwargs)
        return self.fault

    def __exit__(self, *exc_info):
        monkey.disarm(self.fault)
        return False
