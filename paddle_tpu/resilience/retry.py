"""Retry with exponential backoff + jitter + deadline.

Transient failures (shared-FS hiccups, coordination-service races,
checkpoint I/O under preemption pressure) are the norm at pod scale; a
single typed, observable retry primitive replaces ad-hoc try/except
loops. Applied to distributed init (`distributed/parallel.py`),
checkpoint I/O (`resilience/checkpoint.py`), `fleet/utils/fs.py`, and
`utils/download.py`.

Env knobs (defaults, overridable per call site):
    PADDLE_TPU_RETRY_MAX_ATTEMPTS   (default 3)
    PADDLE_TPU_RETRY_BASE_DELAY     seconds, first backoff   (default 0.1)
    PADDLE_TPU_RETRY_MAX_DELAY      seconds, backoff ceiling (default 30)
"""
import errno
import functools
import os
import random
import time

from ..obs import goodput as _goodput
from ..obs import metrics as _obs

DEFAULT_RETRYABLE = (OSError, ConnectionError, TimeoutError)

# Registry-backed retry telemetry: backoff sleeps are wall-clock the
# goodput accountant debits (a pod retrying a flaky FS is not training).
_RETRIES = _obs.counter("paddle_retry_attempts_total",
                        "Retries performed (backoff sleeps)")
_EXHAUSTED = _obs.counter("paddle_retry_exhausted_total",
                          "call_with_retry gave up (RetryError)")

# OSErrors that no amount of waiting fixes: retrying them only adds
# latency, and converting a FileNotFoundError into a RetryError breaks
# every `except OSError`/`except FileNotFoundError` caller contract —
# these always re-raise immediately and unchanged.
PERMANENT_ERRNOS = frozenset({
    errno.ENOENT, errno.ENOTDIR, errno.EISDIR, errno.EEXIST,
    errno.ENAMETOOLONG, errno.EROFS, errno.ENOTEMPTY, errno.EINVAL,
})


def is_permanent(exc):
    return isinstance(exc, OSError) and exc.errno in PERMANENT_ERRNOS


class RetryError(RuntimeError):
    """All attempts exhausted (or deadline hit). `.last` is the final
    exception; it is also chained as __cause__."""

    def __init__(self, message, last=None, attempts=0):
        super().__init__(message)
        self.last = last
        self.attempts = attempts


def _env_int(name, default):
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def backoff_delays(max_attempts, base_delay, max_delay, jitter, rng):
    """Delays slept *between* attempts: base * 2^k, capped, with
    multiplicative jitter in [1-jitter, 1+jitter] (decorrelates a pod's
    worth of workers hammering the same recovering filesystem)."""
    for k in range(max_attempts - 1):
        d = min(max_delay, base_delay * (2.0 ** k))
        if jitter:
            d *= 1.0 + jitter * (2.0 * rng() - 1.0)
        yield max(0.0, d)


def call_with_retry(fn, *args, max_attempts=None, base_delay=None,
                    max_delay=None, deadline=None, retry_on=None,
                    retry_if=None, jitter=0.5, on_retry=None,
                    sleep=time.sleep, rng=random.random, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying on exceptions in
    ``retry_on`` (default: OSError/ConnectionError/TimeoutError).

    deadline: total seconds across attempts+sleeps; exceeded -> RetryError.
    retry_if(exc) -> bool: extra predicate over type-matched exceptions —
    return False to re-raise immediately (for exception types like
    RuntimeError that mix transient and permanent failures).
    on_retry(attempt, exc, delay): observer hook (logging/metrics).
    sleep/rng: injectable for deterministic tests.
    """
    max_attempts = max_attempts if max_attempts is not None else \
        _env_int("PADDLE_TPU_RETRY_MAX_ATTEMPTS", 3)
    base_delay = base_delay if base_delay is not None else \
        _env_float("PADDLE_TPU_RETRY_BASE_DELAY", 0.1)
    max_delay = max_delay if max_delay is not None else \
        _env_float("PADDLE_TPU_RETRY_MAX_DELAY", 30.0)
    retry_on = tuple(retry_on) if retry_on is not None else DEFAULT_RETRYABLE
    if max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
    t0 = time.monotonic()
    delays = backoff_delays(max_attempts, base_delay, max_delay, jitter, rng)
    last = None
    for attempt in range(1, max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if is_permanent(e):
                raise  # unchanged: ENOENT etc. keep their contract
            if retry_if is not None and not retry_if(e):
                raise
            last = e
            if attempt == max_attempts:
                break
            delay = next(delays)
            if deadline is not None and \
                    time.monotonic() - t0 + delay > deadline:
                _EXHAUSTED.inc()
                raise RetryError(
                    f"{_name(fn)}: deadline {deadline}s exceeded after "
                    f"{attempt} attempt(s)", last=e, attempts=attempt) from e
            if on_retry is not None:
                on_retry(attempt, e, delay)
            _RETRIES.inc()
            _goodput.account("retry", delay)
            sleep(delay)
    _EXHAUSTED.inc()
    raise RetryError(
        f"{_name(fn)}: failed after {max_attempts} attempt(s): {last}",
        last=last, attempts=max_attempts) from last


def retry(max_attempts=None, base_delay=None, max_delay=None, deadline=None,
          retry_on=None, jitter=0.5, on_retry=None, sleep=time.sleep,
          rng=random.random):
    """Decorator form of :func:`call_with_retry`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retry(
                fn, *args, max_attempts=max_attempts, base_delay=base_delay,
                max_delay=max_delay, deadline=deadline, retry_on=retry_on,
                jitter=jitter, on_retry=on_retry, sleep=sleep, rng=rng,
                **kwargs)
        return wrapped
    return deco


def _name(fn):
    return getattr(fn, "__qualname__", getattr(fn, "__name__", repr(fn)))
