"""paddle_tpu.resilience — fault-tolerant training runtime.

Five cooperating pieces (ISSUE: ML Productivity Goodput — delivered
throughput is dominated by recovery efficiency, not step time):

- checkpoint: atomic, self-verifying checkpoints with retention GC and
  verified load + fallback (:class:`CheckpointManager`);
- preemption: SIGTERM/maintenance-event handling — save-and-exit at the
  next step boundary with a resumable marker;
- retry: exponential backoff + jitter + deadline for transient I/O and
  coordination failures;
- badstep: in-graph NaN/Inf step skipping + consecutive-bad-step
  rollback policy (:class:`BadStepMonitor`);
- chaos: deterministic fault injection so all of the above stays
  covered by tier-1 CPU tests;
- elastic: pod-scale preemption consensus, straggler detection, and
  dead-host recovery over a small TCP coordinator
  (:func:`elastic.init_from_env`).
"""
from . import chaos  # noqa: F401
from . import elastic  # noqa: F401
from .checkpoint import (  # noqa: F401
    CheckpointCorrupt,
    CheckpointManager,
    atomic_write_bytes,
    atomic_write_json,
    file_sha256,
    leaf_checksums,
)
from .elastic import (  # noqa: F401
    CoordinatorLost,
    ElasticClient,
    ElasticCoordinator,
    LocalElastic,
)
from .preemption import (  # noqa: F401
    EXIT_CODE as PREEMPTED_EXIT_CODE,
    PreemptedExit,
    PreemptionHandler,
    clear_resume_marker,
    get_preemption_handler,
    preemption_requested,
    read_resume_marker,
    resolve_resume_step,
    write_resume_marker,
)
from .retry import RetryError, call_with_retry, retry  # noqa: F401
from .badstep import (  # noqa: F401
    BadStepMonitor,
    guard_step,
    select_tree,
    tree_nonfinite,
)
