"""Minimal numpy evaluator for models produced by paddle_tpu.onnx.export.

Serves two purposes: (1) closes the loop in tests — export → parse the
serialized bytes → execute with numpy → compare against the live model,
proving both the wire encoding and the op semantics; (2) gives users a
dependency-free way to sanity-check an exported model when onnxruntime
isn't installed. Covers exactly the op set the exporter emits.
"""
import math

import numpy as np

from . import wire

_erf = np.vectorize(math.erf, otypes=[np.float64])


def load(path_or_bytes):
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return wire.parse_model(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return wire.parse_model(f.read())


def run(model, feeds):
    """Execute a parsed model dict; feeds: {input_name: ndarray}.
    Returns the list of graph outputs."""
    g = model["graph"] if "graph" in model else model
    values = dict(g["initializers"])
    for inp in g["inputs"]:
        if inp["name"] not in feeds:
            raise KeyError(f"missing feed '{inp['name']}'")
        values[inp["name"]] = np.asarray(feeds[inp["name"]])
    for node in g["nodes"]:
        op = node["op_type"]
        fn = _OPS.get(op)
        if fn is None:
            raise NotImplementedError(f"numpy runner: ONNX op {op}")
        ins = [values[n] for n in node["input"]]
        outs = fn(ins, node["attrs"])
        if not isinstance(outs, (tuple, list)):
            outs = [outs]
        for name, arr in zip(node["output"], outs):
            values[name] = np.asarray(arr)
    return [values[o["name"]] for o in g["outputs"]]


def _unary(fn):
    return lambda ins, attrs: fn(ins[0])


def _binary(fn):
    return lambda ins, attrs: fn(ins[0], ins[1])


def _reduce(fn):
    def h(ins, attrs):
        axes = tuple(int(a) for a in attrs.get("axes", []))
        keep = bool(attrs.get("keepdims", 1))
        return fn(ins[0], axis=axes or None, keepdims=keep)
    return h


def _argreduce(fn):
    def h(ins, attrs):
        axis = int(attrs.get("axis", 0))
        res = fn(ins[0], axis=axis).astype(np.int64)
        if attrs.get("keepdims", 1):
            res = np.expand_dims(res, axis)
        return res
    return h


def _matmul(ins, attrs):
    a, b = ins
    return np.matmul(a, b)


def _conv(ins, attrs):
    x, w = ins[0], ins[1]
    bias = ins[2] if len(ins) > 2 else None
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1])]
    group = int(attrs.get("group", 1))
    pads = [int(p) for p in attrs.get("pads", [0] * 4)]
    nsp = x.ndim - 2
    pad_width = [(0, 0), (0, 0)] + [(pads[i], pads[i + nsp])
                                    for i in range(nsp)]
    xp = np.pad(x, pad_width)
    n, cin = x.shape[:2]
    cout = w.shape[0]
    ksp = w.shape[2:]
    osp = [(xp.shape[2 + i] - (ksp[i] - 1) * dil[i] - 1) // strides[i] + 1
           for i in range(nsp)]
    out = np.zeros([n, cout] + osp, dtype=np.result_type(x, w))
    cin_g, cout_g = cin // group, cout // group
    for g in range(group):
        xg = xp[:, g * cin_g:(g + 1) * cin_g]
        wg = w[g * cout_g:(g + 1) * cout_g]
        for idx in np.ndindex(*osp):
            sl = tuple(
                slice(idx[i] * strides[i],
                      idx[i] * strides[i] + (ksp[i] - 1) * dil[i] + 1,
                      dil[i]) for i in range(nsp))
            patch = xg[(slice(None), slice(None)) + sl]  # [N,Cg,*k]
            out[(slice(None), slice(g * cout_g, (g + 1) * cout_g)) + idx] = \
                np.einsum("nck,ock->no",
                          patch.reshape(patch.shape[0], patch.shape[1], -1),
                          wg.reshape(wg.shape[0], wg.shape[1], -1))
    if bias is not None:
        out += bias.reshape([1, cout] + [1] * nsp)
    return out


def _pool(reducer, init):
    def h(ins, attrs):
        x = ins[0]
        k = [int(v) for v in attrs["kernel_shape"]]
        strides = [int(v) for v in attrs.get("strides", [1] * len(k))]
        pads = [int(p) for p in attrs.get("pads", [0] * (2 * len(k)))]
        nsp = len(k)
        pad_width = [(0, 0), (0, 0)] + [(pads[i], pads[i + nsp])
                                        for i in range(nsp)]
        xp = np.pad(x, pad_width, constant_values=init)
        osp = [(xp.shape[2 + i] - k[i]) // strides[i] + 1
               for i in range(nsp)]
        out = np.zeros(list(x.shape[:2]) + osp, dtype=x.dtype)
        for idx in np.ndindex(*osp):
            sl = tuple(slice(idx[i] * strides[i],
                             idx[i] * strides[i] + k[i])
                       for i in range(nsp))
            patch = xp[(slice(None), slice(None)) + sl]
            out[(slice(None), slice(None)) + idx] = reducer(
                patch.reshape(patch.shape[0], patch.shape[1], -1), -1)
        return out
    return h


def _avgpool(ins, attrs):
    # count_include_pad=1 average (what the exporter emits)
    summed = _pool(np.sum, 0.0)(ins, attrs)
    return summed / float(np.prod([int(v) for v in attrs["kernel_shape"]]))


def _slice(ins, attrs):
    x, starts, ends, axes, steps = (list(ins) + [None, None])[:5]
    starts = [int(v) for v in starts]
    ends = [int(v) for v in ends]
    axes = [int(v) for v in axes] if axes is not None \
        else list(range(len(starts)))
    steps = [int(v) for v in steps] if steps is not None \
        else [1] * len(starts)
    sl = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        lo = None if (sp < 0 and en < -x.shape[ax]) else en
        sl[ax] = slice(st, lo, sp)
    return x[tuple(sl)]


def _pad(ins, attrs):
    x, pads = ins[0], [int(p) for p in ins[1]]
    value = ins[2] if len(ins) > 2 else 0.0
    n = x.ndim
    pad_width = [(pads[i], pads[i + n]) for i in range(n)]
    return np.pad(x, pad_width, constant_values=np.asarray(value).item())


def _cast(ins, attrs):
    return ins[0].astype(wire.np_dtype(int(attrs["to"])))


def _expand(ins, attrs):
    shape = [int(s) for s in ins[1]]
    return np.broadcast_to(ins[0],
                           np.broadcast_shapes(ins[0].shape, tuple(shape)))


def _erf_like(x):
    return _erf(x).astype(x.dtype if x.dtype.kind == "f" else np.float32)


_OPS = {
    "Identity": _unary(lambda x: x),
    "Neg": _unary(np.negative), "Exp": _unary(np.exp), "Log": _unary(np.log),
    "Tanh": _unary(np.tanh),
    "Sigmoid": _unary(lambda x: 1.0 / (1.0 + np.exp(-x))),
    "Sqrt": _unary(np.sqrt), "Abs": _unary(np.abs), "Sign": _unary(np.sign),
    "Floor": _unary(np.floor), "Ceil": _unary(np.ceil),
    "Round": _unary(np.round), "Erf": _unary(_erf_like),
    "Reciprocal": _unary(np.reciprocal), "Not": _unary(np.logical_not),
    "Add": _binary(np.add), "Sub": _binary(np.subtract),
    # integer Div truncates toward zero (C semantics, matching jax's
    # `div` primitive and the ONNX spec) — numpy // floors instead
    "Mul": _binary(np.multiply), "Div": _binary(
        lambda a, b: (np.trunc(np.divide(a, b)).astype(a.dtype)
                      if a.dtype.kind in "iu" else a / b)),
    "Max": _binary(np.maximum), "Min": _binary(np.minimum),
    "Pow": _binary(np.power),
    "Mod": lambda ins, attrs: (np.fmod if attrs.get("fmod") else np.mod)(
        ins[0], ins[1]),
    "Greater": _binary(np.greater), "Less": _binary(np.less),
    "GreaterOrEqual": _binary(np.greater_equal),
    "LessOrEqual": _binary(np.less_equal), "Equal": _binary(np.equal),
    "And": _binary(np.logical_and), "Or": _binary(np.logical_or),
    "Xor": _binary(np.logical_xor),
    "ReduceSum": _reduce(np.sum), "ReduceMax": _reduce(np.max),
    "ReduceMin": _reduce(np.min), "ReduceProd": _reduce(np.prod),
    "ArgMax": _argreduce(np.argmax), "ArgMin": _argreduce(np.argmin),
    "MatMul": _matmul, "Conv": _conv,
    "MaxPool": _pool(np.max, -np.inf), "AveragePool": _avgpool,
    "Transpose": lambda ins, attrs: np.transpose(
        ins[0], [int(p) for p in attrs["perm"]]),
    "Reshape": lambda ins, attrs: ins[0].reshape(
        [int(s) for s in ins[1]]),
    "Expand": _expand,
    "Concat": lambda ins, attrs: np.concatenate(
        ins, axis=int(attrs["axis"])),
    "Slice": _slice, "Pad": _pad, "Cast": _cast,
    "Split": lambda ins, attrs: np.split(
        ins[0], np.cumsum([int(s) for s in attrs["split"]])[:-1],
        axis=int(attrs.get("axis", 0))),
    "Where": lambda ins, attrs: np.where(ins[0], ins[1], ins[2]),
    "Gather": lambda ins, attrs: np.take(
        ins[0], ins[1].astype(np.int64), axis=int(attrs.get("axis", 0))),
    "Clip": lambda ins, attrs: np.clip(ins[0], ins[1], ins[2]),
}
