"""Minimal protobuf wire-format writer/reader for the ONNX schema subset
the exporter emits (reference: python/paddle/onnx/export.py delegates to
paddle2onnx + the onnx pip package; neither is in this image, so the
serialization is done directly against the stable ONNX wire format).

Only what `paddle_tpu.onnx.export` produces is supported: ModelProto /
GraphProto / NodeProto / TensorProto(raw_data) / AttributeProto /
ValueInfoProto with dense-tensor types. Field numbers follow
onnx/onnx.proto (IR version 7, stable since 2020).
"""
import struct

import numpy as np

# ONNX TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

_NP2ONNX = {
    "float32": FLOAT, "uint8": UINT8, "int8": INT8, "uint16": UINT16,
    "int16": INT16, "int32": INT32, "int64": INT64, "bool": BOOL,
    "float16": FLOAT16, "float64": DOUBLE, "uint32": UINT32,
    "uint64": UINT64, "bfloat16": BFLOAT16,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR = 1, 2, 3, 4
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


def onnx_dtype(np_dtype):
    name = np.dtype(np_dtype).name if not isinstance(np_dtype, str) else np_dtype
    if name not in _NP2ONNX:
        raise ValueError(f"dtype {name} has no ONNX mapping")
    return _NP2ONNX[name]


def np_dtype(onnx_type):
    return np.dtype(_ONNX2NP[onnx_type])


# ------------------------------------------------------------------ encode

def _varint(n):
    n &= (1 << 64) - 1  # negatives as 64-bit two's complement
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field, wire_type):
    return _varint((field << 3) | wire_type)


def f_int(field, value):
    return _key(field, 0) + _varint(int(value))


def f_float(field, value):
    return _key(field, 5) + struct.pack("<f", float(value))


def f_bytes(field, payload):
    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    return _key(field, 2) + _varint(len(payload)) + payload


def tensor_proto(name, arr):
    arr = np.ascontiguousarray(arr)
    out = b"".join(f_int(1, d) for d in arr.shape)
    out += f_int(2, onnx_dtype(arr.dtype))
    out += f_bytes(8, name)
    out += f_bytes(9, arr.astype(arr.dtype.newbyteorder("<")).tobytes())
    return out


def attribute_proto(name, value):
    out = f_bytes(1, name)
    if isinstance(value, bool):
        out += f_int(3, int(value)) + f_int(20, A_INT)
    elif isinstance(value, int):
        out += f_int(3, value) + f_int(20, A_INT)
    elif isinstance(value, float):
        out += f_float(2, value) + f_int(20, A_FLOAT)
    elif isinstance(value, str):
        out += f_bytes(4, value) + f_int(20, A_STRING)
    elif isinstance(value, np.ndarray):
        out += f_bytes(5, tensor_proto(name, value)) + f_int(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            out += b"".join(f_float(7, v) for v in value) + f_int(20, A_FLOATS)
        elif all(isinstance(v, str) for v in value) and value:
            out += b"".join(f_bytes(9, v) for v in value) + f_int(20, A_STRINGS)
        else:
            out += b"".join(f_int(8, int(v)) for v in value) + f_int(20, A_INTS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return out


def node_proto(op_type, inputs, outputs, name="", attrs=None):
    out = b"".join(f_bytes(1, i) for i in inputs)
    out += b"".join(f_bytes(2, o) for o in outputs)
    if name:
        out += f_bytes(3, name)
    out += f_bytes(4, op_type)
    for k, v in (attrs or {}).items():
        out += f_bytes(5, attribute_proto(k, v))
    return out


def value_info_proto(name, elem_type, shape):
    dims = b"".join(f_bytes(1, f_int(1, d)) for d in shape)  # dim_value only
    tensor_type = f_int(1, elem_type) + f_bytes(2, dims)
    type_proto = f_bytes(1, tensor_type)
    return f_bytes(1, name) + f_bytes(2, type_proto)


def graph_proto(name, nodes, initializers, inputs, outputs):
    """nodes: serialized NodeProto bytes; initializers: {name: ndarray};
    inputs/outputs: [(name, elem_type, shape)]."""
    out = b"".join(f_bytes(1, n) for n in nodes)
    out += f_bytes(2, name)
    out += b"".join(f_bytes(5, tensor_proto(k, v))
                    for k, v in initializers.items())
    out += b"".join(f_bytes(11, value_info_proto(*i)) for i in inputs)
    out += b"".join(f_bytes(12, value_info_proto(*o)) for o in outputs)
    return out


def model_proto(graph, opset_version, producer="paddle_tpu", ir_version=7):
    opset = f_bytes(1, "") + f_int(2, opset_version)
    return (f_int(1, ir_version) + f_bytes(2, producer) + f_bytes(3, "0.0")
            + f_bytes(7, graph) + f_bytes(8, opset))


# ------------------------------------------------------------------ decode

def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 63:  # negative int64
                result -= 1 << 64
            return result, pos
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a serialized message."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            value, pos = _read_varint(buf, pos)
        elif wt == 2:
            length, pos = _read_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wt == 5:
            value = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wt == 1:
            value = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, value


def parse_tensor(buf):
    dims, dtype, name, raw = [], FLOAT, "", b""
    for field, _, value in _fields(buf):
        if field == 1:
            dims.append(value)
        elif field == 2:
            dtype = value
        elif field == 8:
            name = bytes(value).decode("utf-8")
        elif field == 9:
            raw = bytes(value)
    arr = np.frombuffer(raw, dtype=np_dtype(dtype)).reshape(dims)
    return name, arr


def parse_attribute(buf):
    name, atype, val = "", None, {}
    for field, _, value in _fields(buf):
        if field == 1:
            name = bytes(value).decode("utf-8")
        elif field == 2:
            val["f"] = value
        elif field == 3:
            val["i"] = value
        elif field == 4:
            val["s"] = bytes(value).decode("utf-8")
        elif field == 5:
            val["t"] = parse_tensor(value)[1]
        elif field == 7:
            val.setdefault("floats", []).append(value)
        elif field == 8:
            val.setdefault("ints", []).append(value)
        elif field == 9:
            val.setdefault("strings", []).append(
                bytes(value).decode("utf-8"))
        elif field == 20:
            atype = value
    if atype == A_FLOAT:
        return name, val["f"]
    if atype == A_INT:
        return name, val["i"]
    if atype == A_STRING:
        return name, val["s"]
    if atype == A_TENSOR:
        return name, val["t"]
    if atype == A_FLOATS:
        return name, val.get("floats", [])
    if atype == A_INTS:
        return name, val.get("ints", [])
    if atype == A_STRINGS:
        return name, val.get("strings", [])
    raise ValueError(f"attribute {name}: unsupported type {atype}")


def parse_node(buf):
    node = {"input": [], "output": [], "op_type": "", "name": "", "attrs": {}}
    for field, _, value in _fields(buf):
        if field == 1:
            node["input"].append(bytes(value).decode("utf-8"))
        elif field == 2:
            node["output"].append(bytes(value).decode("utf-8"))
        elif field == 3:
            node["name"] = bytes(value).decode("utf-8")
        elif field == 4:
            node["op_type"] = bytes(value).decode("utf-8")
        elif field == 5:
            k, v = parse_attribute(value)
            node["attrs"][k] = v
    return node


def _parse_value_info(buf):
    name, elem_type, shape = "", None, []
    for field, _, value in _fields(buf):
        if field == 1:
            name = bytes(value).decode("utf-8")
        elif field == 2:
            for f2, _, tt in _fields(value):
                if f2 == 1:  # tensor_type
                    for f3, _, v3 in _fields(tt):
                        if f3 == 1:
                            elem_type = v3
                        elif f3 == 2:  # shape
                            for f4, _, dim in _fields(v3):
                                if f4 == 1:
                                    for f5, _, v5 in _fields(dim):
                                        if f5 == 1:
                                            shape.append(v5)
    return {"name": name, "elem_type": elem_type, "shape": shape}


def parse_graph(buf):
    g = {"name": "", "nodes": [], "initializers": {}, "inputs": [],
         "outputs": []}
    for field, _, value in _fields(buf):
        if field == 1:
            g["nodes"].append(parse_node(value))
        elif field == 2:
            g["name"] = bytes(value).decode("utf-8")
        elif field == 5:
            name, arr = parse_tensor(value)
            g["initializers"][name] = arr
        elif field == 11:
            g["inputs"].append(_parse_value_info(value))
        elif field == 12:
            g["outputs"].append(_parse_value_info(value))
    return g


def parse_model(buf):
    model = {"ir_version": None, "opset": None, "graph": None,
             "producer": ""}
    for field, _, value in _fields(buf):
        if field == 1:
            model["ir_version"] = value
        elif field == 2:
            model["producer"] = bytes(value).decode("utf-8")
        elif field == 7:
            model["graph"] = parse_graph(value)
        elif field == 8:
            for f2, _, v2 in _fields(value):
                if f2 == 2:
                    model["opset"] = v2
    return model
