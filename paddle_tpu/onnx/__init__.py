"""paddle.onnx (reference: python/paddle/onnx/export.py). ONNX export from
XLA requires an ONNX writer dependency not in this image; the API is
present and raises with guidance (jit.save's StableHLO is the portable
interchange format here)."""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "onnx export requires the onnx package (not in this environment); "
        "use paddle_tpu.jit.save for portable StableHLO export")
