"""paddle.onnx (reference: python/paddle/onnx/export.py). The reference
delegates to paddle2onnx; here the Layer is traced to a jaxpr and lowered
directly to ONNX (wire.py hand-encodes the protobuf — the onnx package is
not in this image). runner.py is a numpy evaluator for exported models.
"""
from .export import export, export_bytes, UnsupportedOp  # noqa: F401
from .runner import load, run  # noqa: F401

__all__ = ["export", "export_bytes", "load", "run", "UnsupportedOp"]
